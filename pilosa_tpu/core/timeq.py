"""Time quantum views (reference: time.go).

A time field fans each write out to one view per quantum unit
(`standard_2019`, `standard_201901`, ...); range queries walk the minimal
set of views covering [start, end) — coarse units in the middle, fine units
at the ragged edges (reference: viewsByTimeRange time.go:104-180).
"""

import datetime as dt

TIME_FORMAT = "%Y-%m-%dT%H:%M"  # reference: TimeFormat (pilosa.go)

VALID_QUANTUMS = {"Y", "YM", "YMD", "YMDH", "M", "MD", "MDH", "D", "DH", "H", ""}

_UNIT_FMT = {"Y": "%Y", "M": "%Y%m", "D": "%Y%m%d", "H": "%Y%m%d%H"}


class InvalidTimeQuantum(ValueError):
    pass


def validate_quantum(q):
    if q not in VALID_QUANTUMS:
        raise InvalidTimeQuantum(f"invalid time quantum: {q!r}")
    return q


def parse_time(value):
    """Parse a PQL timestamp: 'YYYY-MM-DDTHH:MM' string or unix seconds."""
    if isinstance(value, dt.datetime):
        return value
    if isinstance(value, str):
        return dt.datetime.strptime(value, TIME_FORMAT)
    if isinstance(value, (int, float)):
        return dt.datetime.fromtimestamp(int(value), dt.timezone.utc).replace(tzinfo=None)
    raise ValueError("arg must be a timestamp")


def view_by_time_unit(name, t, unit):
    fmt = _UNIT_FMT.get(unit)
    return f"{name}_{t.strftime(fmt)}" if fmt else ""


def views_by_time(name, t, quantum):
    """All views a write at time t lands in (reference: viewsByTime)."""
    return [view_by_time_unit(name, t, u) for u in quantum if u in _UNIT_FMT]


def _add_month(t):
    # reference addMonth: clamp late-month days to the 1st to avoid skipping
    # a month (Jan 31 + 1mo != Mar 2).
    if t.day > 28:
        t = t.replace(day=1)
    if t.month == 12:
        return t.replace(year=t.year + 1, month=1)
    return t.replace(month=t.month + 1)


def _next_year_gte(t, end):
    nxt = t.replace(year=t.year + 1)
    return nxt.year == end.year or end > nxt


def _next_month_gte(t, end):
    nxt = _add_month_exact(t)
    return (nxt.year, nxt.month) == (end.year, end.month) or end > nxt


def _add_month_exact(t):
    # Go's AddDate(0,1,0) normalizes overflow (Jan 31 -> Mar 2/3); only used
    # inside the GTE checks where the reference uses AddDate directly.
    month = t.month + 1
    year = t.year + (month - 1) // 12
    month = (month - 1) % 12 + 1
    try:
        return t.replace(year=year, month=month)
    except ValueError:
        # overflow day-of-month like Go's normalization
        days_over = 0
        while True:
            days_over += 1
            try:
                base = t.replace(year=year, month=month, day=t.day - days_over)
                return base + dt.timedelta(days=days_over)
            except ValueError:
                continue


def _next_day_gte(t, end):
    nxt = t + dt.timedelta(days=1)
    return (nxt.year, nxt.month, nxt.day) == (end.year, end.month, end.day) or end > nxt


def views_by_time_range(name, start, end, quantum):
    """Minimal view list covering [start, end) (reference: viewsByTimeRange)."""
    has_y = "Y" in quantum
    has_m = "M" in quantum
    has_d = "D" in quantum
    has_h = "H" in quantum

    t = start
    results = []

    # Walk up from the smallest units at the ragged start edge.
    if has_h or has_d or has_m:
        while t < end:
            if has_h:
                if not _next_day_gte(t, end):
                    break
                if t.hour != 0:
                    results.append(view_by_time_unit(name, t, "H"))
                    t = t + dt.timedelta(hours=1)
                    continue
            if has_d:
                if not _next_month_gte(t, end):
                    break
                if t.day != 1:
                    results.append(view_by_time_unit(name, t, "D"))
                    t = t + dt.timedelta(days=1)
                    continue
            if has_m:
                if not _next_year_gte(t, end):
                    break
                if t.month != 1:
                    results.append(view_by_time_unit(name, t, "M"))
                    t = _add_month(t)
                    continue
            break

    # Walk back down from the largest units.
    while t < end:
        if has_y and _next_year_gte(t, end):
            results.append(view_by_time_unit(name, t, "Y"))
            t = t.replace(year=t.year + 1)
        elif has_m and _next_month_gte(t, end):
            results.append(view_by_time_unit(name, t, "M"))
            t = _add_month(t)
        elif has_d and _next_day_gte(t, end):
            results.append(view_by_time_unit(name, t, "D"))
            t = t + dt.timedelta(days=1)
        elif has_h:
            results.append(view_by_time_unit(name, t, "H"))
            t = t + dt.timedelta(hours=1)
        else:
            break

    return results


def view_time_part(view_name, base):
    """The trailing time digits of a quantum view name ('' if none)."""
    if not view_name.startswith(base + "_"):
        return ""
    part = view_name[len(base) + 1:]
    return part if part.isdigit() else ""


def min_max_views(view_names, quantum, base):
    """(min, max) view names among `view_names` at the quantum's COARSEST
    unit (reference: minMaxViews time.go:240 — 4 chars for Y, 6 for M,
    8 for D, 10 for H). (None, None) when no time views exist."""
    if "Y" in quantum:
        chars = 4
    elif "M" in quantum:
        chars = 6
    elif "D" in quantum:
        chars = 8
    elif "H" in quantum:
        chars = 10
    else:
        return None, None
    matching = sorted(
        v for v in view_names if len(view_time_part(v, base)) == chars)
    if not matching:
        return None, None
    return matching[0], matching[-1]


def time_of_view(view_name, base, adj=False):
    """The start time a quantum view covers; with adj=True, the end
    (start of the NEXT unit) — reference: timeOfView time.go:279."""
    part = view_time_part(view_name, base)
    fmts = {4: "%Y", 6: "%Y%m", 8: "%Y%m%d", 10: "%Y%m%d%H"}
    fmt = fmts.get(len(part))
    if fmt is None:
        raise ValueError(f"not a time view: {view_name!r}")
    t = dt.datetime.strptime(part, fmt)
    if adj:
        if len(part) == 4:
            t = t.replace(year=t.year + 1)
        elif len(part) == 6:
            t = _add_month(t)
        elif len(part) == 8:
            t = t + dt.timedelta(days=1)
        else:
            t = t + dt.timedelta(hours=1)
    return t
