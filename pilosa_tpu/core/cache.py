"""Per-fragment TopN row caches.

Reference: cache.go — `rankCache` (sorted, threshold-pruned, :136-301) and
`lruCache` (:58-130), selected by the field's cache type ranked/lru/none
(field.go:1647-1649, defaults ranked/50k field.go:45-48), persisted to
`.cache` files (fragment.go:461-502,2403) and flushed periodically
(holder.go:506-549).

TPU-native role: the dense-plane TopN recomputes exact counts on device, so
the cache is a *candidate selector* — it bounds how many row planes get
stacked and popcounted per TopN, exactly the approximation the reference
makes (executor TopN consults only cached rows).
"""

import os
import threading

import numpy as np

CACHE_TYPE_RANKED = "ranked"
CACHE_TYPE_LRU = "lru"
CACHE_TYPE_NONE = "none"

DEFAULT_CACHE_SIZE = 50_000  # reference: defaultCacheSize field.go:48

# Rank cache prunes when it grows past this factor of max entries
# (reference: thresholdFactor cache.go:33).
_PRUNE_FACTOR = 1.1


class RankCache:
    """Top-count cache with threshold pruning (reference: cache.go:136)."""

    def __init__(self, max_entries=DEFAULT_CACHE_SIZE):
        self.max_entries = int(max_entries)
        self._entries = {}  # id -> count
        self._threshold = 0
        self._lock = threading.RLock()

    def __len__(self):
        return len(self._entries)

    def add(self, id, count):
        id, count = int(id), int(count)
        with self._lock:
            if count == 0:
                self._entries.pop(id, None)
                return
            if (id not in self._entries and self._threshold
                    and count < self._threshold
                    and len(self._entries) >= self.max_entries):
                return  # below the pruned floor; not worth tracking
            self._entries[id] = count
            if len(self._entries) > self.max_entries * _PRUNE_FACTOR:
                self._prune()

    def bulk_add(self, ids, counts):
        for id, count in zip(ids, counts):
            self.add(id, count)

    def get(self, id):
        return self._entries.get(int(id), 0)

    def ids(self):
        """Cached row ids, highest count first (candidate order)."""
        with self._lock:
            return [id for id, _ in sorted(
                self._entries.items(), key=lambda kv: (-kv[1], kv[0]))]

    def top(self):
        """[(id, count)] sorted by count desc, id asc."""
        with self._lock:
            return sorted(self._entries.items(), key=lambda kv: (-kv[1], kv[0]))

    def invalidate(self, id):
        with self._lock:
            self._entries.pop(int(id), None)

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._threshold = 0

    def _prune(self):
        keep = sorted(self._entries.items(), key=lambda kv: (-kv[1], kv[0]))
        keep = keep[:self.max_entries]
        self._entries = dict(keep)
        self._threshold = keep[-1][1] if keep else 0


class LRUCache:
    """LRU row->count cache (reference: lruCache cache.go:58)."""

    def __init__(self, max_entries=DEFAULT_CACHE_SIZE):
        from collections import OrderedDict

        self.max_entries = int(max_entries)
        self._entries = OrderedDict()
        self._lock = threading.RLock()

    def __len__(self):
        return len(self._entries)

    def add(self, id, count):
        id, count = int(id), int(count)
        with self._lock:
            if count == 0:
                self._entries.pop(id, None)
                return
            self._entries[id] = count
            self._entries.move_to_end(id)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def bulk_add(self, ids, counts):
        for id, count in zip(ids, counts):
            self.add(id, count)

    def get(self, id):
        with self._lock:
            count = self._entries.get(int(id), 0)
            if count:
                self._entries.move_to_end(int(id))
            return count

    def ids(self):
        with self._lock:
            return [id for id, _ in sorted(
                self._entries.items(), key=lambda kv: (-kv[1], kv[0]))]

    def top(self):
        with self._lock:
            return sorted(self._entries.items(), key=lambda kv: (-kv[1], kv[0]))

    def invalidate(self, id):
        with self._lock:
            self._entries.pop(int(id), None)

    def clear(self):
        with self._lock:
            self._entries.clear()


def new_cache(cache_type, cache_size=DEFAULT_CACHE_SIZE):
    """Factory by field cache type (reference: field.go:1647-1649)."""
    if cache_type == CACHE_TYPE_RANKED:
        return RankCache(cache_size)
    if cache_type == CACHE_TYPE_LRU:
        return LRUCache(cache_size)
    if cache_type in (CACHE_TYPE_NONE, "", None):
        return None
    raise ValueError(f"unknown cache type: {cache_type!r}")


def save_cache(cache, path):
    """Persist (ids, counts) to a .cache file (reference:
    fragment.flushCache fragment.go:2403 — protobuf pairs; here npz)."""
    if cache is None or len(cache) == 0:
        if os.path.exists(path):
            os.remove(path)
        return
    pairs = cache.top()
    ids = np.array([p[0] for p in pairs], dtype=np.uint64)
    counts = np.array([p[1] for p in pairs], dtype=np.uint64)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, ids=ids, counts=counts)
    os.replace(tmp, path)


def load_cache(cache, path):
    """Load persisted pairs into cache; silently skips missing/corrupt
    files (reference: openCache fragment.go:461 logs and continues)."""
    if cache is None or not os.path.exists(path):
        return
    try:
        with np.load(path) as data:
            cache.bulk_add(data["ids"].tolist(), data["counts"].tolist())
    except Exception:
        pass
