"""Row: a cross-shard query result (reference: row.go:27).

The reference's Row is a list of per-shard rowSegments each wrapping a
roaring bitmap, merged during reduce. Here a Row holds per-shard dense
planes (host numpy; device arrays live only inside the executor's jitted
call trees) plus optional attrs/keys decoration for responses.
"""

import numpy as np

from ..shardwidth import SHARD_WIDTH, WORDS_PER_ROW
from ..roaring.containers import popcount32


class Row:
    __slots__ = ("segments", "attrs", "keys")

    def __init__(self, segments=None):
        # shard -> [WORDS_PER_ROW] uint32 plane
        self.segments = segments or {}
        self.attrs = None
        self.keys = None

    @classmethod
    def from_columns(cls, columns):
        """Build from absolute column ids (test/import convenience)."""
        columns = np.asarray(columns, dtype=np.uint64)
        row = cls()
        shards = columns // np.uint64(SHARD_WIDTH)
        for shard in np.unique(shards):
            offs = (columns[shards == shard] % np.uint64(SHARD_WIDTH)).astype(np.int64)
            plane = np.zeros(WORDS_PER_ROW, dtype=np.uint32)
            np.bitwise_or.at(
                plane, offs // 32, np.uint32(1) << (offs % 32).astype(np.uint32))
            row.segments[int(shard)] = plane
        return row

    def merge(self, other):
        """Union-merge segments from another Row (reference: Row.Merge
        row.go:67)."""
        for shard, plane in other.segments.items():
            mine = self.segments.get(shard)
            if mine is None:
                self.segments[shard] = plane
            else:
                self.segments[shard] = mine | plane
        return self

    def count(self):
        return int(sum(
            int(popcount32(p).sum()) for p in self.segments.values()))

    def any(self):
        return any(p.any() for p in self.segments.values())

    def columns(self):
        """Sorted absolute column ids."""
        out = []
        for shard in sorted(self.segments):
            plane = self.segments[shard]
            nz = np.nonzero(plane)[0]
            if len(nz) == 0:
                continue
            bits = np.unpackbits(
                plane[nz].view(np.uint8).reshape(-1, 4), axis=1,
                bitorder="little")
            w, b = np.nonzero(bits)
            out.append(nz[w].astype(np.uint64) * 32 + b.astype(np.uint64)
                       + np.uint64(shard * SHARD_WIDTH))
        if not out:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(out)

    def shards(self):
        return sorted(self.segments)

    def __eq__(self, other):
        if not isinstance(other, Row):
            return NotImplemented
        mine = {s: p for s, p in self.segments.items() if p.any()}
        theirs = {s: p for s, p in other.segments.items() if p.any()}
        if mine.keys() != theirs.keys():
            return False
        return all(np.array_equal(mine[s], theirs[s]) for s in mine)

    def __repr__(self):
        return f"<Row count={self.count()} shards={self.shards()}>"
