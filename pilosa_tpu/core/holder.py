"""Holder: root container for all data on a node (reference: holder.go:50).

Opens/closes indexes from the data directory, owns the snapshot queue (the
background persister, reference: fragment.go:187-241), and exposes schema.
"""

import logging
import os
import queue
import shutil
import threading

from .field import FieldOptions
from .index import Index, IndexOptions, validate_name


class HolderError(Exception):
    pass


class SnapshotQueue:
    """Single background worker persisting fragments whose op log exceeded
    max_op_n (reference: newSnapshotQueue fragment.go:187). Bounded queue;
    enqueue degrades to synchronous snapshot when full (the reference logs
    and skips; synchronous is safer)."""

    def __init__(self, size=100):
        self._queue = queue.Queue(maxsize=size)
        self._thread = None
        self._stop = threading.Event()

    def start(self):
        self._thread = threading.Thread(
            target=self._worker, name="snapshot-queue", daemon=True)
        self._thread.start()
        return self

    def _worker(self):
        while not self._stop.is_set():
            try:
                frag = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                if frag.is_open and frag.op_n > 0:
                    frag.snapshot()
            except Exception:
                logging.getLogger("pilosa_tpu").exception(
                    "snapshot failed for %r", frag)
            finally:
                self._queue.task_done()

    def enqueue(self, fragment):
        try:
            self._queue.put_nowait(fragment)
        except queue.Full:
            fragment.snapshot()

    def stop(self):
        if self._thread is None:
            return
        self._queue.join()
        self._stop.set()
        self._thread.join(timeout=5)
        self._thread = None


class Holder:
    def __init__(self, path, max_op_n=None, use_snapshot_queue=True,
                 cache_flush_interval=60.0):
        self.path = path
        self.max_op_n = max_op_n
        self.indexes = {}
        # set by the TranslateReplicator before indexes open so replica
        # stores come up read-only with the primary-forward hook installed
        self.translate_configurer = None
        self.snapshot_queue = SnapshotQueue() if use_snapshot_queue else None
        # periodic TopN cache persistence (reference: holder.go:506-549);
        # <=0 disables the ticker (fragments still flush on close)
        self.cache_flush_interval = cache_flush_interval
        self._flush_stop = None
        self._flush_thread = None
        self._lock = threading.RLock()
        self.opened = False

    # -- lifecycle ----------------------------------------------------------

    def open(self):
        """(reference: Holder.Open holder.go:137) Scan data dir and open
        every index."""
        os.makedirs(self.path, exist_ok=True)
        if self.snapshot_queue:
            self.snapshot_queue.start()
        for name in sorted(os.listdir(self.path)):
            sub = os.path.join(self.path, name)
            if os.path.isdir(sub):
                self._new_index(name).open()
        if self.cache_flush_interval > 0:
            self._flush_stop = threading.Event()
            self._flush_thread = threading.Thread(
                target=self._flush_worker, daemon=True,
                name="cache-flush")
            self._flush_thread.start()
        self.opened = True
        return self

    def _flush_worker(self):
        while not self._flush_stop.wait(self.cache_flush_interval):
            try:
                self.flush_caches()
            except Exception:
                pass  # flush is best-effort; fragments also flush on close

    def close(self):
        with self._lock:
            if self._flush_thread is not None:
                self._flush_stop.set()
                self._flush_thread.join(timeout=5)
                self._flush_thread = None
            if self.snapshot_queue:
                self.snapshot_queue.stop()
            for idx in self.indexes.values():
                idx.close()
            self.indexes.clear()
            self.opened = False

    def reopen(self):
        """Close and reopen from disk (test harness parity: test/pilosa.go:120)."""
        self.close()
        self.snapshot_queue = SnapshotQueue() if self.snapshot_queue is not None else None
        return self.open()

    # -- TopN caches ---------------------------------------------------------

    def _all_fragments(self):
        for idx in list(self.indexes.values()):
            for field in list(idx.fields.values()):
                for view in list(field.views.values()):
                    yield from view.fragments.values()

    def flush_caches(self):
        """Persist every fragment's TopN cache (reference: holder cache
        flush ticker holder.go:506-549)."""
        for frag in self._all_fragments():
            frag.flush_cache()

    def recalculate_caches(self):
        """(reference: Holder.RecalculateCaches holder.go:553)"""
        for frag in self._all_fragments():
            frag.recalculate_cache()

    # -- durability ----------------------------------------------------------

    def sync_fragments(self):
        """fsync every open fragment's WAL file. Called before an oplog
        checkpoint: once the fragments below the log are durable, the
        checkpointed prefix truly never needs replaying."""
        n = 0
        for frag in self._all_fragments():
            try:
                frag.sync()
                n += 1
            except Exception:
                logging.getLogger("pilosa_tpu").exception(
                    "fsync failed for %r", frag)
        return n

    def replay_oplog(self, oplog, apply, logger=None):
        """Boot-time crash recovery: feed every unapplied oplog record
        through ``apply(lsn, record)`` in LSN order. A record that fails
        is logged and counted, not fatal — one poisoned record must not
        keep the node from booting (same stance as torn-tail
        truncation). Returns ``(applied, failed)``."""
        from ..utils import flightrec

        applied = failed = 0
        first = last = None
        for lsn, record in oplog.replay():
            if first is None:
                first = lsn
            last = lsn
            try:
                apply(lsn, record)
                applied += 1
            except Exception as e:  # noqa: BLE001 — count, don't wedge boot
                failed += 1
                if logger is not None:
                    logger.printf(
                        "oplog replay: record lsn=%d (%s) failed: %s",
                        lsn, record.get("kind"), e)
            finally:
                # failed records advance the watermark too: they are
                # deterministic failures, not transient ones, and must
                # not pin the checkpoint (they were counted above)
                oplog.mark_applied(lsn)
        if applied or failed:
            flightrec.record("oplog.replay", first_lsn=first, last_lsn=last,
                             applied=applied, failed=failed)
            if logger is not None:
                logger.printf(
                    "oplog replay: %d applied, %d failed (lsn %s..%s)",
                    applied, failed, first, last)
        return applied, failed

    # -- indexes ------------------------------------------------------------

    def _new_index(self, name):
        idx = Index(
            os.path.join(self.path, name), name, max_op_n=self.max_op_n,
            snapshot_queue=self.snapshot_queue,
            translate_configurer=self.translate_configurer)
        self.indexes[name] = idx
        return idx

    def translate_stores(self):
        """Every live translate store (index column + field row stores)."""
        for idx in list(self.indexes.values()):
            if idx.translate_store is not None:
                yield idx.translate_store
            for field in list(idx.fields.values()):
                if field.translate_store is not None:
                    yield field.translate_store

    def index(self, name):
        return self.indexes.get(name)

    def create_index(self, name, options=None, if_not_exists=False):
        """(reference: Holder.CreateIndex holder.go:379)"""
        validate_name(name)
        with self._lock:
            existing = self.indexes.get(name)
            if existing is not None:
                if if_not_exists:
                    return existing
                raise HolderError(f"index already exists: {name}")
            idx = self._new_index(name)
            idx.options = options or IndexOptions()
            idx.open()
            return idx

    def delete_index(self, name):
        with self._lock:
            idx = self.indexes.pop(name, None)
            if idx is None:
                raise HolderError(f"index not found: {name}")
            idx.close()
            shutil.rmtree(idx.path, ignore_errors=True)

    # -- schema -------------------------------------------------------------

    def schema(self):
        """Serializable schema description (reference: Holder.Schema)."""
        out = []
        for iname in sorted(self.indexes):
            idx = self.indexes[iname]
            fields = []
            for fname in sorted(idx.public_fields()):
                f = idx.fields[fname]
                fields.append({
                    "name": fname,
                    "options": f.options.to_dict(),
                    "shards": f.available_shards(),
                })
            out.append({
                "name": iname,
                "options": idx.options.to_dict(),
                "fields": fields,
            })
        return out

    def apply_schema(self, schema):
        """Create any missing indexes/fields from a schema description
        (cluster DDL sync; reference: api.ApplySchema/holder merge)."""
        for idx_desc in schema:
            idx = self.create_index(
                idx_desc["name"],
                options=IndexOptions.from_dict(idx_desc.get("options", {})),
                if_not_exists=True)
            for f_desc in idx_desc.get("fields", []):
                idx.create_field(
                    f_desc["name"],
                    options=FieldOptions.from_dict(f_desc.get("options", {})),
                    if_not_exists=True)
