"""View: a layout variant of a field, grouping per-shard fragments.

Reference: view.go:44. Names: "standard", time-quantum views
("standard_2019", ...), and "bsig_<field>" for BSI integer storage
(view.go:27-41).
"""

import itertools
import os
import threading

from .fragment import Fragment

_view_uids = itertools.count(1)

VIEW_STANDARD = "standard"
VIEW_BSI_GROUP_PREFIX = "bsig_"


class View:
    def __init__(self, path, index, field, name, max_op_n=None,
                 snapshot_queue=None, mutexed=False, cache_type="none",
                 cache_size=0):
        self.path = path  # .../<field>/views/<name>
        self.index = index
        self.field = field
        self.name = name
        self.mutexed = mutexed
        self.max_op_n = max_op_n
        self.snapshot_queue = snapshot_queue
        # BSI views never cache (only row-oriented views serve TopN)
        self.cache_type = ("none" if name.startswith(VIEW_BSI_GROUP_PREFIX)
                           else cache_type)
        self.cache_size = cache_size
        self.fragments = {}  # shard -> Fragment
        self._lock = threading.RLock()
        # O(1) change fingerprint for the stacked serving caches: bumped
        # on ANY fragment mutation or creation in this view, so a cache
        # hit costs one counter compare instead of a per-shard generation
        # walk (exec/stacked.py two-level fingerprint). uid distinguishes
        # a recreated view (drop + re-create) whose counter restarts.
        self.uid = next(_view_uids)
        self.mutations = 0

    def open(self):
        frag_dir = os.path.join(self.path, "fragments")
        os.makedirs(frag_dir, exist_ok=True)
        for name in sorted(os.listdir(frag_dir)):
            if name.endswith(".snapshotting") or name.endswith(".cache"):
                continue
            try:
                shard = int(name)
            except ValueError:
                continue
            self._new_fragment(shard).open()
        return self

    def close(self):
        with self._lock:
            for f in self.fragments.values():
                f.close()
            self.fragments.clear()
            self._bump_mutations()

    def remove_fragment(self, shard):
        """Detach and return one fragment (resize cleanup). Bumps the
        mutation counter — removal changes what cached serving stacks
        must contain, exactly like a write (exec/stacked.py stamp)."""
        with self._lock:
            frag = self.fragments.pop(shard, None)
            if frag is not None:
                self._bump_mutations()
            return frag

    def fragment_path(self, shard):
        return os.path.join(self.path, "fragments", str(shard))

    def _new_fragment(self, shard):
        kwargs = {}
        if self.max_op_n is not None:
            kwargs["max_op_n"] = self.max_op_n
        frag = Fragment(
            self.fragment_path(shard), self.index, self.field, self.name,
            shard, snapshot_queue=self.snapshot_queue, mutexed=self.mutexed,
            cache_type=self.cache_type, cache_size=self.cache_size,
            **kwargs)
        frag.on_mutate = self._bump_mutations
        self.fragments[shard] = frag
        self._bump_mutations()
        return frag

    def _bump_mutations(self):
        # benign-race increment: a stale read in the serving cache means
        # one extra generation walk, never a stale result (the per-shard
        # gens remain the ground truth)
        self.mutations += 1

    def fragment(self, shard):
        return self.fragments.get(shard)

    def create_fragment_if_not_exists(self, shard):
        """(reference: view.CreateFragmentIfNotExists view.go:263)"""
        with self._lock:
            frag = self.fragments.get(shard)
            if frag is None:
                frag = self._new_fragment(shard)
                frag.open()
            return frag

    def available_shards(self):
        return sorted(self.fragments.keys())

    # -- routed ops ---------------------------------------------------------

    def set_bit(self, row_id, column_id):
        from ..shardwidth import SHARD_WIDTH

        shard = column_id // SHARD_WIDTH
        return self.create_fragment_if_not_exists(shard).set_bit(row_id, column_id)

    def clear_bit(self, row_id, column_id):
        from ..shardwidth import SHARD_WIDTH

        shard = column_id // SHARD_WIDTH
        frag = self.fragment(shard)
        if frag is None:
            return False
        return frag.clear_bit(row_id, column_id)

    def set_value(self, column_id, bit_depth, value):
        from ..shardwidth import SHARD_WIDTH

        shard = column_id // SHARD_WIDTH
        return self.create_fragment_if_not_exists(shard).set_value(
            column_id, bit_depth, value)

    def clear_value(self, column_id, bit_depth):
        from ..shardwidth import SHARD_WIDTH

        shard = column_id // SHARD_WIDTH
        frag = self.fragment(shard)
        if frag is None:
            return False
        return frag.clear_value(column_id, bit_depth)

    def value(self, column_id, bit_depth):
        from ..shardwidth import SHARD_WIDTH

        shard = column_id // SHARD_WIDTH
        frag = self.fragment(shard)
        if frag is None:
            return 0, False
        return frag.value(column_id, bit_depth)
