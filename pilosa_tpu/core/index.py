"""Index: a namespace of fields over a shared column space.

Reference: index.go:37. Holds fields, column attributes, the optional
`_exists` existence field used by Not() queries (track_existence;
reference: index.go:215, holder.go:46), and the column-keys option.
"""

import json
import os
import re
import threading

from .field import Field, FieldOptions

EXISTENCE_FIELD_NAME = "_exists"  # reference: holder.go:46

_NAME_RE = re.compile(r"^[a-z][a-z0-9_-]{0,63}$")  # reference: pilosa.go:121


class IndexError_(Exception):
    pass


def validate_name(name):
    if not _NAME_RE.match(name):
        raise IndexError_(
            f"invalid name {name!r}: must match [a-z][a-z0-9_-]{{0,63}}")
    return name


class IndexOptions:
    def __init__(self, keys=False, track_existence=True):
        self.keys = keys
        self.track_existence = track_existence

    def to_dict(self):
        return dict(self.__dict__)

    @classmethod
    def from_dict(cls, d):
        return cls(**d)


class Index:
    def __init__(self, path, name, options=None, max_op_n=None,
                 snapshot_queue=None, column_attr_store=None,
                 row_attr_stores=None, translate_configurer=None):
        self.path = path
        self.name = name
        self.options = options or IndexOptions()
        self.max_op_n = max_op_n
        self.snapshot_queue = snapshot_queue
        self.fields = {}
        self.column_attr_store = column_attr_store
        self.translate_store = None  # column key translation when keys=True
        # called with each new translate store (replication wiring: sets
        # read-only + the remote-create hook before any write can race)
        self.translate_configurer = translate_configurer
        self._row_attr_stores = row_attr_stores or {}
        self._lock = threading.RLock()

    @property
    def meta_path(self):
        return os.path.join(self.path, ".meta")

    @property
    def keys(self):
        return self.options.keys

    def open(self):
        from ..storage import SqliteAttrStore, SqliteTranslateStore

        os.makedirs(self.path, exist_ok=True)
        if os.path.exists(self.meta_path):
            with open(self.meta_path) as f:
                self.options = IndexOptions.from_dict(json.load(f))
        else:
            self.save_meta()
        if self.column_attr_store is None:
            self.column_attr_store = SqliteAttrStore(
                os.path.join(self.path, ".attrs.db"))
        if self.options.keys and self.translate_store is None:
            self.translate_store = SqliteTranslateStore(
                os.path.join(self.path, ".keys.db"), index=self.name)
            if self.translate_configurer is not None:
                self.translate_configurer(self.translate_store)
        for name in sorted(os.listdir(self.path)):
            sub = os.path.join(self.path, name)
            if os.path.isdir(sub) and os.path.exists(os.path.join(sub, ".meta")):
                self._new_field(name).open()
        if self.options.track_existence and EXISTENCE_FIELD_NAME not in self.fields:
            self._create_existence_field()
        return self

    def save_meta(self):
        os.makedirs(self.path, exist_ok=True)
        with open(self.meta_path, "w") as f:
            json.dump(self.options.to_dict(), f)

    def close(self):
        with self._lock:
            for f in self.fields.values():
                f.close()
            self.fields.clear()
            if self.column_attr_store is not None:
                self.column_attr_store.close()
                self.column_attr_store = None
            if self.translate_store is not None:
                self.translate_store.close()
                self.translate_store = None

    # -- fields -------------------------------------------------------------

    def _new_field(self, name, options=None):
        field = Field(
            os.path.join(self.path, name), self.name, name, options=options,
            max_op_n=self.max_op_n, snapshot_queue=self.snapshot_queue,
            row_attr_store=self._row_attr_stores.get(name),
            translate_configurer=self.translate_configurer)
        self.fields[name] = field
        return field

    def _create_existence_field(self):
        field = self._new_field(EXISTENCE_FIELD_NAME, FieldOptions(
            cache_type="none", cache_size=0))
        field.open()
        return field

    def field(self, name):
        return self.fields.get(name)

    def existence_field(self):
        return self.fields.get(EXISTENCE_FIELD_NAME)

    def create_field(self, name, options=None, if_not_exists=False):
        """(reference: Index.CreateField index.go:351)"""
        validate_name(name)
        with self._lock:
            existing = self.fields.get(name)
            if existing is not None:
                if if_not_exists:
                    return existing
                raise IndexError_(f"field already exists: {name}")
            field = self._new_field(name, options or FieldOptions())
            field.open()
            return field

    def delete_field(self, name):
        import shutil

        with self._lock:
            field = self.fields.pop(name, None)
            if field is None:
                raise IndexError_(f"field not found: {name}")
            field.close()
            shutil.rmtree(field.path, ignore_errors=True)

    def public_fields(self):
        return {n: f for n, f in self.fields.items()
                if n != EXISTENCE_FIELD_NAME}

    # -- shards -------------------------------------------------------------

    def available_shards(self):
        """(reference: Index.AvailableShards index.go:292)"""
        shards = set()
        for f in self.fields.values():
            shards.update(f.available_shards())
        return sorted(shards)

    # -- existence tracking --------------------------------------------------

    def add_existence(self, column_ids):
        if not self.options.track_existence:
            return
        field = self.existence_field()
        if field is None:
            field = self._create_existence_field()
        import numpy as np

        column_ids = np.asarray(column_ids, dtype=np.uint64)
        field.import_bits(np.zeros(len(column_ids), dtype=np.uint64), column_ids)
