"""Field: a typed collection of rows (reference: field.go:65).

Types (reference: field.go:56-62): set, int, time, mutex, bool. Options
mirror the reference's functional options (OptFieldType* field.go:127-204):
cache type/size for set fields, min/max/base+bitDepth for int fields, time
quantum (+noStandardView) for time fields.

Metadata persists as JSON in <field>/.meta (the reference uses a protobuf
.meta — internal/private.proto FieldOptions).
"""

import json
import os
import threading

import numpy as np

from . import timeq
from .fragment import (
    BSI_EXISTS_BIT,
    BSI_OFFSET_BIT,
    BSI_SIGN_BIT,
    FALSE_ROW_ID,
    TRUE_ROW_ID,
)
from .view import VIEW_BSI_GROUP_PREFIX, VIEW_STANDARD, View

FIELD_TYPE_SET = "set"
FIELD_TYPE_INT = "int"
FIELD_TYPE_TIME = "time"
FIELD_TYPE_MUTEX = "mutex"
FIELD_TYPE_BOOL = "bool"

CACHE_TYPE_RANKED = "ranked"
CACHE_TYPE_LRU = "lru"
CACHE_TYPE_NONE = "none"

DEFAULT_CACHE_TYPE = CACHE_TYPE_RANKED
DEFAULT_CACHE_SIZE = 50_000


class FieldError(Exception):
    pass


def bsi_base(min_value, max_value):
    """Default base offset (reference: bsiBase field.go:1550)."""
    if min_value > 0:
        return min_value
    if max_value < 0:
        return max_value
    return 0


def bit_depth(uvalue):
    return max(int(uvalue).bit_length(), 1)


def bit_depth_range(min_value, max_value, base):
    return max(
        bit_depth(abs(min_value - base)), bit_depth(abs(max_value - base)))


class FieldOptions:
    def __init__(self, type=FIELD_TYPE_SET, cache_type=DEFAULT_CACHE_TYPE,
                 cache_size=DEFAULT_CACHE_SIZE, min=0, max=0, base=None,
                 bit_depth=0, time_quantum="", no_standard_view=False,
                 keys=False):
        self.type = type
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.min = min
        self.max = max
        self.base = bsi_base(min, max) if base is None else base
        self.bit_depth = bit_depth
        self.time_quantum = time_quantum
        self.no_standard_view = no_standard_view
        self.keys = keys

    def to_dict(self):
        return dict(self.__dict__)

    @classmethod
    def from_dict(cls, d):
        return cls(**d)

    @classmethod
    def int_field(cls, min=-(1 << 31), max=(1 << 31) - 1):
        base = bsi_base(min, max)
        return cls(type=FIELD_TYPE_INT, min=min, max=max, base=base,
                   bit_depth=bit_depth_range(min, max, base),
                   cache_type=CACHE_TYPE_NONE, cache_size=0)

    @classmethod
    def time_field(cls, quantum, no_standard_view=False, keys=False):
        timeq.validate_quantum(quantum)
        return cls(type=FIELD_TYPE_TIME, time_quantum=quantum,
                   no_standard_view=no_standard_view,
                   cache_type=CACHE_TYPE_NONE, cache_size=0, keys=keys)

    @classmethod
    def mutex_field(cls, cache_type=DEFAULT_CACHE_TYPE,
                    cache_size=DEFAULT_CACHE_SIZE, keys=False):
        return cls(type=FIELD_TYPE_MUTEX, cache_type=cache_type,
                   cache_size=cache_size, keys=keys)

    @classmethod
    def bool_field(cls):
        return cls(type=FIELD_TYPE_BOOL, cache_type=CACHE_TYPE_NONE,
                   cache_size=0)


class Field:
    def __init__(self, path, index_name, name, options=None,
                 max_op_n=None, snapshot_queue=None, row_attr_store=None,
                 translate_configurer=None):
        self.path = path
        self.index_name = index_name
        self.name = name
        self.options = options or FieldOptions()
        self.max_op_n = max_op_n
        self.snapshot_queue = snapshot_queue
        self.views = {}  # name -> View
        self.row_attr_store = row_attr_store
        self.translate_store = None  # row key translation when keys=True
        self.translate_configurer = translate_configurer
        self._lock = threading.RLock()

    # -- lifecycle ----------------------------------------------------------

    @property
    def meta_path(self):
        return os.path.join(self.path, ".meta")

    def open(self):
        from ..storage import SqliteAttrStore, SqliteTranslateStore

        os.makedirs(self.path, exist_ok=True)
        if os.path.exists(self.meta_path):
            with open(self.meta_path) as f:
                self.options = FieldOptions.from_dict(json.load(f))
        else:
            self.save_meta()
        if self.row_attr_store is None:
            self.row_attr_store = SqliteAttrStore(
                os.path.join(self.path, ".attrs.db"))
        if self.options.keys and self.translate_store is None:
            self.translate_store = SqliteTranslateStore(
                os.path.join(self.path, ".keys.db"),
                index=self.index_name, field=self.name)
            if self.translate_configurer is not None:
                self.translate_configurer(self.translate_store)
        views_dir = os.path.join(self.path, "views")
        if os.path.isdir(views_dir):
            for name in sorted(os.listdir(views_dir)):
                self._new_view(name).open()
        return self

    def save_meta(self):
        os.makedirs(self.path, exist_ok=True)
        with open(self.meta_path, "w") as f:
            json.dump(self.options.to_dict(), f)

    def close(self):
        with self._lock:
            for v in self.views.values():
                v.close()
            self.views.clear()
            if self.row_attr_store is not None:
                self.row_attr_store.close()
                self.row_attr_store = None
            if self.translate_store is not None:
                self.translate_store.close()
                self.translate_store = None

    # -- views --------------------------------------------------------------

    def _new_view(self, name):
        view = View(
            os.path.join(self.path, "views", name), self.index_name,
            self.name, name, max_op_n=self.max_op_n,
            snapshot_queue=self.snapshot_queue,
            mutexed=self.options.type in (FIELD_TYPE_MUTEX, FIELD_TYPE_BOOL),
            cache_type=self.options.cache_type,
            cache_size=self.options.cache_size)
        self.views[name] = view
        return view

    def view(self, name=VIEW_STANDARD):
        return self.views.get(name)

    def create_view_if_not_exists(self, name):
        with self._lock:
            view = self.views.get(name)
            if view is None:
                view = self._new_view(name)
                view.open()
            return view

    def bsi_view_name(self):
        return VIEW_BSI_GROUP_PREFIX + self.name

    @property
    def type(self):
        return self.options.type

    def time_quantum(self):
        return self.options.time_quantum

    def available_shards(self):
        shards = set()
        for v in self.views.values():
            shards.update(v.available_shards())
        return sorted(shards)

    # -- bit ops ------------------------------------------------------------

    def set_bit(self, row_id, column_id, timestamp=None):
        """(reference: Field.SetBit field.go:927)"""
        if self.type == FIELD_TYPE_INT:
            raise FieldError(f"set_bit unsupported for field type {self.type}")
        changed = False
        if not self.options.no_standard_view:
            changed |= self.create_view_if_not_exists(VIEW_STANDARD).set_bit(
                row_id, column_id)
        if timestamp is not None:
            if self.type != FIELD_TYPE_TIME:
                raise FieldError(
                    f"cannot set timestamp on {self.type} field")
            for name in timeq.views_by_time(
                    VIEW_STANDARD, timestamp, self.time_quantum()):
                changed |= self.create_view_if_not_exists(name).set_bit(
                    row_id, column_id)
        return changed

    def clear_bit(self, row_id, column_id):
        if self.type == FIELD_TYPE_INT:
            raise FieldError(f"clear_bit unsupported for field type {self.type}")
        changed = False
        for name, view in list(self.views.items()):
            if name.startswith(VIEW_BSI_GROUP_PREFIX):
                continue
            changed |= view.clear_bit(row_id, column_id)
        return changed

    # -- BSI value ops ------------------------------------------------------

    def _require_int(self):
        if self.type != FIELD_TYPE_INT:
            raise FieldError(f"bsiGroup not found on field type {self.type}")

    def set_value(self, column_id, value):
        """(reference: Field.SetValue field.go:1075) value stored
        base-adjusted sign-magnitude; grows bitDepth on demand."""
        self._require_int()
        opts = self.options
        value = int(value)
        if value < opts.min:
            raise FieldError(f"value {value} below field minimum {opts.min}")
        if value > opts.max:
            raise FieldError(f"value {value} above field maximum {opts.max}")
        base_value = value - opts.base
        required = bit_depth(abs(base_value))
        if required > opts.bit_depth:
            opts.bit_depth = required
            self.save_meta()
        view = self.create_view_if_not_exists(self.bsi_view_name())
        return view.set_value(column_id, opts.bit_depth, base_value)

    def clear_value(self, column_id):
        self._require_int()
        view = self.view(self.bsi_view_name())
        if view is None:
            return False
        return view.clear_value(column_id, self.options.bit_depth)

    def value(self, column_id):
        self._require_int()
        view = self.view(self.bsi_view_name())
        if view is None:
            return 0, False
        v, exists = view.value(column_id, self.options.bit_depth)
        return (v + self.options.base, True) if exists else (0, False)

    # -- bool convenience ---------------------------------------------------

    def set_bool(self, column_id, value):
        return self.set_bit(TRUE_ROW_ID if value else FALSE_ROW_ID, column_id)

    # -- bulk import --------------------------------------------------------

    def import_bits(self, row_ids, column_ids, timestamps=None, clear=False):
        """Bulk import grouped by shard (reference: Field.Import
        field.go:1204). Timestamps fan rows out to quantum views."""
        from ..shardwidth import SHARD_WIDTH

        row_ids = np.asarray(row_ids, dtype=np.uint64)
        column_ids = np.asarray(column_ids, dtype=np.uint64)
        if len(row_ids) != len(column_ids):
            raise FieldError("mismatched row/column lengths")

        # view name -> (rows, cols) selections
        work = {}
        if timestamps is None:
            work[VIEW_STANDARD] = (row_ids, column_ids)
        else:
            if self.type != FIELD_TYPE_TIME:
                raise FieldError("timestamps on non-time field")
            by_view = {}
            for i, ts in enumerate(timestamps):
                if ts is None:
                    # Untimed bits always land in the standard view, even
                    # under no_standard_view (reference: Field.Import routes
                    # zero-timestamp bits to viewStandard, field.go:1242).
                    by_view.setdefault(VIEW_STANDARD, []).append(i)
                    continue
                for name in timeq.views_by_time(
                        VIEW_STANDARD, ts, self.time_quantum()):
                    by_view.setdefault(name, []).append(i)
            if not self.options.no_standard_view:
                work[VIEW_STANDARD] = (row_ids, column_ids)
                by_view.pop(VIEW_STANDARD, None)
            for name, idxs in by_view.items():
                idxs = np.asarray(idxs, dtype=np.int64)
                work[name] = (row_ids[idxs], column_ids[idxs])

        changed = 0
        for name, (rows, cols) in work.items():
            view = self.create_view_if_not_exists(name)
            shards = cols // np.uint64(SHARD_WIDTH)
            for shard in np.unique(shards):
                sel = shards == shard
                frag = view.create_fragment_if_not_exists(int(shard))
                changed += frag.bulk_import(rows[sel], cols[sel], clear=clear)
        return changed

    def import_values(self, column_ids, values, clear=False):
        """Bulk BSI import (reference: Field.importValue field.go:1285).
        clear=True removes the stored value of every listed column (the
        values are ignored; reference: fragment.importValue's clear arg
        fragment.go:2205)."""
        from ..shardwidth import SHARD_WIDTH

        self._require_int()
        if clear:
            changed = 0
            for col in np.asarray(column_ids, dtype=np.uint64).tolist():
                changed += bool(self.clear_value(int(col)))
            return changed
        opts = self.options
        column_ids = np.asarray(column_ids, dtype=np.uint64)
        values = np.asarray(values, dtype=np.int64)
        if len(values) and (values.min() < opts.min or values.max() > opts.max):
            raise FieldError("value out of range for field")
        base_values = values - opts.base
        if len(values):
            required = bit_depth(int(np.abs(base_values).max()))
            if required > opts.bit_depth:
                opts.bit_depth = required
                self.save_meta()
        view = self.create_view_if_not_exists(self.bsi_view_name())
        shards = column_ids // np.uint64(SHARD_WIDTH)
        changed = 0
        for shard in np.unique(shards):
            sel = shards == shard
            frag = view.create_fragment_if_not_exists(int(shard))
            to_set, to_clear = [], []
            for col, bval in zip(column_ids[sel], base_values[sel]):
                s, c = frag.positions_for_value(
                    int(col), opts.bit_depth, int(bval))
                to_set.extend(s)
                to_clear.extend(c)
            changed += frag.import_positions(to_set, to_clear)
        return changed
