"""Protobuf serializer for the query data plane.

Wire-compatible with the reference (encoding/proto/proto.go:29-45
Serializer; QueryResult type tags :1055-1067), so a stock Pilosa client
POSTing `Content-Type: application/x-protobuf` QueryRequests receives
byte-compatible QueryResponses. Regenerate bindings with
`protoc --python_out=. pilosa.proto` in this directory.
"""

from . import pilosa_pb2 as pb

CONTENT_TYPE_PROTOBUF = "application/x-protobuf"

# QueryResult.Type tags (reference: encoding/proto/proto.go:1055-1067)
TYPE_NIL = 0
TYPE_ROW = 1
TYPE_PAIRS = 2
TYPE_VALCOUNT = 3
TYPE_UINT64 = 4
TYPE_BOOL = 5
TYPE_ROWIDS = 6
TYPE_GROUPCOUNTS = 7
TYPE_ROWIDENTIFIERS = 8
TYPE_PAIR = 9


# -- requests ---------------------------------------------------------------

def encode_query_request(query, shards=None, remote=False,
                         column_attrs=False, exclude_row_attrs=False,
                         exclude_columns=False):
    m = pb.QueryRequest(Query=query, Remote=remote, ColumnAttrs=column_attrs,
                        ExcludeRowAttrs=exclude_row_attrs,
                        ExcludeColumns=exclude_columns)
    if shards:
        m.Shards.extend(int(s) for s in shards)
    return m.SerializeToString()


def decode_query_request(data):
    m = pb.QueryRequest.FromString(data)
    return {
        "query": m.Query,
        "shards": list(m.Shards) or None,
        "remote": m.Remote,
        "column_attrs": m.ColumnAttrs,
        "exclude_row_attrs": m.ExcludeRowAttrs,
        "exclude_columns": m.ExcludeColumns,
    }


# -- results ----------------------------------------------------------------

def _encode_result(result, out):
    from ..core.row import Row
    from ..exec.result import GroupCount, Pair, RowIdentifiers, ValCount

    if result is None:
        out.Type = TYPE_NIL
    elif isinstance(result, Row):
        out.Type = TYPE_ROW
        out.Row.Columns.extend(int(c) for c in result.columns())
        if result.keys is not None:
            out.Row.Keys.extend(result.keys)
    elif isinstance(result, bool):
        out.Type = TYPE_BOOL
        out.Changed = result
    elif isinstance(result, int):
        out.Type = TYPE_UINT64
        out.N = result
    elif isinstance(result, ValCount):
        out.Type = TYPE_VALCOUNT
        out.ValCount.Val = result.val
        out.ValCount.Count = result.count
    elif isinstance(result, Pair):
        out.Type = TYPE_PAIR
        _set_pair(out.Pairs.add(), result)
    elif isinstance(result, RowIdentifiers):
        out.Type = TYPE_ROWIDENTIFIERS
        out.RowIdentifiers.Rows.extend(int(r) for r in result.rows)
        if result.keys is not None:
            out.RowIdentifiers.Keys.extend(result.keys)
    elif isinstance(result, list) and result and isinstance(
            result[0], GroupCount):
        out.Type = TYPE_GROUPCOUNTS
        for gc in result:
            g = out.GroupCounts.add()
            g.Count = gc.count
            for fr in gc.group:
                f = g.Group.add()
                f.Field = fr.field
                f.RowID = fr.row_id
                if fr.row_key is not None:
                    f.RowKey = fr.row_key
    elif isinstance(result, list):
        # Pairs (TopN) — possibly empty; empty lists encode as empty pairs
        out.Type = TYPE_PAIRS
        for p in result:
            _set_pair(out.Pairs.add(), p)
    else:
        raise ValueError(f"unencodable result type {type(result)!r}")


def _set_pair(slot, p):
    slot.ID = p.id
    slot.Count = p.count
    if p.key is not None:
        slot.Key = p.key


def _decode_result(m):
    from ..exec.result import (
        FieldRow, GroupCount, Pair, RowIdentifiers, ValCount)

    t = m.Type
    if t == TYPE_NIL:
        return None
    if t == TYPE_ROW:
        out = {"columns": list(m.Row.Columns)}
        if m.Row.Keys:
            out["keys"] = list(m.Row.Keys)
        return out
    if t == TYPE_BOOL:
        return m.Changed
    if t == TYPE_UINT64:
        return m.N
    if t == TYPE_VALCOUNT:
        return ValCount(m.ValCount.Val, m.ValCount.Count)
    if t == TYPE_PAIR:
        p = m.Pairs[0]
        return Pair(p.ID, p.Count, p.Key or None)
    if t == TYPE_PAIRS:
        return [Pair(p.ID, p.Count, p.Key or None) for p in m.Pairs]
    if t == TYPE_ROWIDENTIFIERS:
        return RowIdentifiers(
            list(m.RowIdentifiers.Rows),
            list(m.RowIdentifiers.Keys) or None)
    if t == TYPE_GROUPCOUNTS:
        return [GroupCount(
            [FieldRow(f.Field, f.RowID, f.RowKey or None) for f in g.Group],
            g.Count) for g in m.GroupCounts]
    raise ValueError(f"unknown QueryResult type {t}")


# Attr type tags (reference: attr.go:27-30)
_ATTR_STRING, _ATTR_INT, _ATTR_BOOL, _ATTR_FLOAT = 1, 2, 3, 4


def _encode_attrs(attrs, slot_adder):
    for key, value in sorted(attrs.items()):
        a = slot_adder()
        a.Key = key
        if isinstance(value, bool):
            a.Type, a.BoolValue = _ATTR_BOOL, value
        elif isinstance(value, int):
            a.Type, a.IntValue = _ATTR_INT, value
        elif isinstance(value, float):
            a.Type, a.FloatValue = _ATTR_FLOAT, value
        else:
            a.Type, a.StringValue = _ATTR_STRING, str(value)


def _decode_attrs(pb_attrs):
    out = {}
    for a in pb_attrs:
        if a.Type == _ATTR_BOOL:
            out[a.Key] = a.BoolValue
        elif a.Type == _ATTR_INT:
            out[a.Key] = a.IntValue
        elif a.Type == _ATTR_FLOAT:
            out[a.Key] = a.FloatValue
        else:
            out[a.Key] = a.StringValue
    return out


def encode_query_response(results, err=None, column_attr_sets=None):
    m = pb.QueryResponse()
    if err:
        m.Err = str(err)
    for r in results or []:
        _encode_result(r, m.Results.add())
    for cas in column_attr_sets or []:
        slot = m.ColumnAttrSets.add()
        slot.ID = cas["id"]
        _encode_attrs(cas.get("attrs") or {}, slot.Attrs.add)
    return m.SerializeToString()


def decode_query_response(data):
    """-> (results list, err string or None). Row results decode to the
    JSON-ish dict shape (columns/keys) since the wire Row has no segment
    structure. Use decode_query_response_full for column attr sets."""
    results, err, _ = decode_query_response_full(data)
    return results, err


def decode_query_response_full(data):
    """-> (results, err, column_attr_sets)."""
    m = pb.QueryResponse.FromString(data)
    attr_sets = [{"id": c.ID, "attrs": _decode_attrs(c.Attrs)}
                 for c in m.ColumnAttrSets]
    return ([_decode_result(r) for r in m.Results], m.Err or None,
            attr_sets)
