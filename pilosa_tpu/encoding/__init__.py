"""Wire encoding (reference: encoding/proto/proto.go Serializer +
internal/public.proto). JSON is the default HTTP encoding; this package adds
the protobuf data plane, wire-compatible with the reference."""

from .serializer import (  # noqa: F401
    CONTENT_TYPE_PROTOBUF,
    decode_query_request,
    decode_query_response,
    encode_query_request,
    encode_query_response,
)
