"""Anti-entropy: replica repair (reference: holderSyncer holder.go:911,
fragmentSyncer fragment.go:2861).

Walks the local schema; for every fragment whose shard this node owns, it
compares per-100-row block checksums with the other replica owners, merges
differing blocks to majority consensus (ties count as set — reference:
mergeBlock fragment.go:1875, majorityN=(n+1)/2), applies the local delta
directly and pushes each remote's delta back via the import-roaring path.
Index/field attributes sync by block-checksum diff + bulk merge
(reference: syncIndex holder.go:975, syncField holder.go:1021).
"""

import logging
import threading

import numpy as np

from ..roaring import Bitmap, serialize
from ..shardwidth import SHARD_WIDTH

logger = logging.getLogger("pilosa_tpu.syncer")


def merge_block(fragment, block_id, pair_sets):
    """Merge one hash block across replicas to majority consensus.

    pair_sets: list of (row_ids, column_ids) arrays from each REMOTE
    replica (column ids are shard-relative offsets, as block_data
    returns). The local fragment is replica 0. Applies the local delta
    in place; returns [(set_positions, clear_positions)] per remote.
    (reference: fragment.mergeBlock fragment.go:1875)
    """
    from ..core.fragment import HASH_BLOCK_SIZE

    lo = block_id * HASH_BLOCK_SIZE * SHARD_WIDTH
    hi = (block_id + 1) * HASH_BLOCK_SIZE * SHARD_WIDTH

    # Hold the fragment lock across read + local apply so a concurrent
    # import can't produce a torn snapshot of the block.
    with fragment._lock:
        local = fragment.storage.slice_range(lo, hi).astype(np.uint64)
        all_pos = [local]
        for rows, cols in pair_sets:
            rows = np.asarray(rows, dtype=np.uint64)
            cols = np.asarray(cols, dtype=np.uint64)
            pos = rows * np.uint64(SHARD_WIDTH) + cols
            pos = pos[(pos >= lo) & (pos < hi)]
            all_pos.append(np.unique(pos))

        majority = (len(all_pos) + 1) // 2
        if len(all_pos) > 1:
            uniq, counts = np.unique(
                np.concatenate(all_pos), return_counts=True)
            consensus = uniq[counts >= majority]
        else:
            consensus = local

        deltas = []
        for pos in all_pos:
            sets = np.setdiff1d(consensus, pos, assume_unique=True)
            clears = np.setdiff1d(pos, consensus, assume_unique=True)
            deltas.append((sets, clears))

        local_sets, local_clears = deltas[0]
        if len(local_sets) or len(local_clears):
            fragment.import_positions(local_sets, local_clears)
    return deltas[1:]


def _positions_to_roaring(positions):
    bm = Bitmap()
    bm.add_many(np.asarray(positions, dtype=np.uint64))
    return serialize(bm)


class FragmentSyncer:
    """Sync one fragment with its replica owners (reference:
    fragmentSyncer fragment.go:2832)."""

    def __init__(self, fragment, index_name, cluster, client_factory,
                 is_closing=None):
        self.fragment = fragment
        self.index_name = index_name
        self.cluster = cluster
        self.client_factory = client_factory
        self.is_closing = is_closing or (lambda: False)

    def _peers(self):
        nodes = self.cluster.shard_nodes(self.index_name, self.fragment.shard)
        return [n for n in nodes if n.id != self.cluster.local_id]

    def sync_fragment(self):
        """Block-checksum diff, then per-block merge (reference:
        syncFragment fragment.go:2861)."""
        from .client import ClientError

        peers = self._peers()
        if not peers:
            return 0
        f = self.fragment
        local_blocks = dict(f.blocks())  # id -> checksum bytes
        peer_blocks = []
        for node in peers:
            if self.is_closing():
                return 0
            try:
                resp = self.client_factory(node.uri).fragment_blocks(
                    self.index_name, f.field, f.view, f.shard)
                blocks = {b["id"]: bytes.fromhex(b["checksum"])
                          for b in resp.get("blocks", [])}
            except ClientError as e:
                if e.status != 404:
                    # unreachable peer: abort rather than treat it as empty
                    # (reference: syncFragment returns on any error except
                    # ErrFragmentNotFound fragment.go:2883)
                    logger.warning("abort sync of %s/%s/%s/%s: %s",
                                   self.index_name, f.field, f.view,
                                   f.shard, e)
                    return 0
                # 404: fragment genuinely absent on the replica -> empty
                blocks = {}
            except Exception as e:
                logger.warning("abort sync of %s/%s/%s/%s: %s",
                               self.index_name, f.field, f.view, f.shard, e)
                return 0
            peer_blocks.append(blocks)

        block_ids = set(local_blocks)
        for blocks in peer_blocks:
            block_ids.update(blocks)
        synced = 0
        for bid in sorted(block_ids):
            if self.is_closing():
                break
            chks = [local_blocks.get(bid)] + [b.get(bid) for b in peer_blocks]
            if len({c for c in chks}) <= 1:
                continue  # all replicas agree (including all-missing)
            self.sync_block(bid)
            synced += 1
        return synced

    def sync_block(self, block_id):
        """Fetch the block from every peer, merge to consensus, push each
        peer's delta back via import-roaring (reference: syncBlock
        fragment.go:2941)."""
        from .client import ClientError

        f = self.fragment
        peers = self._peers()
        pair_sets = []
        for node in peers:
            try:
                resp = self.client_factory(node.uri).fragment_block_data(
                    self.index_name, f.field, f.view, f.shard, block_id)
                pair_sets.append((resp.get("rowIDs", []),
                                  resp.get("columnIDs", [])))
            except ClientError as e:
                if e.status != 404:
                    # A fetch failure must NOT count as an empty replica:
                    # with RF>=3 that would vote to clear live bits
                    # (reference: syncBlock aborts on error fragment.go:2966).
                    logger.warning("abort block %d sync: %s", block_id, e)
                    return
                pair_sets.append(([], []))
            except Exception as e:
                logger.warning("abort block %d sync: %s", block_id, e)
                return

        deltas = merge_block(f, block_id, pair_sets)

        for node, (sets, clears) in zip(peers, deltas):
            client = self.client_factory(node.uri)
            try:
                if len(sets):
                    client.import_roaring(
                        self.index_name, f.field, f.shard,
                        _positions_to_roaring(sets), view=f.view, remote=True)
                if len(clears):
                    client.import_roaring(
                        self.index_name, f.field, f.shard,
                        _positions_to_roaring(clears), clear=True,
                        view=f.view, remote=True)
            except Exception:
                logger.exception("pushing block %d delta to %s",
                                 block_id, node.id)


class HolderSyncer:
    """Synchronize all local data with the cluster (reference:
    holderSyncer holder.go:888)."""

    def __init__(self, holder, cluster, client_factory, is_closing=None):
        self.holder = holder
        self.cluster = cluster
        self.client_factory = client_factory
        self.is_closing = is_closing or (lambda: False)
        self._lock = threading.Lock()

    def sync_holder(self):
        """(reference: SyncHolder holder.go:911) Returns fragments synced."""
        with self._lock:
            total = 0
            for iname in sorted(self.holder.indexes):
                if self.is_closing():
                    return total
                idx = self.holder.indexes[iname]
                self._sync_attrs(idx.column_attr_store, iname)
                shards = idx.available_shards()
                for fname in sorted(idx.fields):
                    if self.is_closing():
                        return total
                    field = idx.fields[fname]
                    self._sync_attrs(field.row_attr_store, iname, fname)
                    for view in list(field.views.values()):
                        for shard in shards:
                            if self.is_closing():
                                return total
                            if not self.cluster.owns_shard(
                                    self.cluster.local_id, iname, shard):
                                continue
                            frag = view.fragment(shard)
                            if frag is None:
                                continue
                            total += FragmentSyncer(
                                frag, iname, self.cluster,
                                self.client_factory,
                                self.is_closing).sync_fragment()
            return total

    def _sync_attrs(self, store, index_name, field_name=""):
        """Block-diff attr merge with every peer (reference: syncIndex
        holder.go:975 / syncField holder.go:1021). One POST of our block
        checksums per peer; the peer answers with attrs from every block
        that differs (the reference's attr/diff protocol, handler.go:
        312,315) — one round trip instead of blocks + N block fetches.
        Peers without the diff route fall back to the pull protocol."""
        from .client import ClientError

        if store is None:
            return
        blocks = [{"id": bid, "checksum": chk}
                  for bid, chk in store.blocks()]
        for node in self.cluster.peers():
            if self.is_closing():
                return
            client = self.client_factory(node.uri)
            try:
                data = client.attr_diff(index_name, blocks,
                                        field=field_name)
            except ClientError as e:
                if e.status not in (404, 405):
                    continue  # peer refused; don't retry another way
                # route absent on the peer: pull protocol
                data = self._pull_attr_diff(
                    client, index_name, field_name,
                    {b["id"]: b["checksum"] for b in blocks})
                if data is None:
                    continue
            except Exception:
                continue  # unreachable peer: a second request would
                #           just wait out another timeout
            merged = {int(id_str): attrs for id_str, attrs
                      in data.get("attrs", {}).items()}
            if merged:
                store.set_bulk_attrs(merged)
                blocks = [{"id": bid, "checksum": chk}
                          for bid, chk in store.blocks()]

    @staticmethod
    def _pull_attr_diff(client, index_name, field_name, local):
        """Fallback pull protocol: peer's block list, then each
        differing block's data."""
        try:
            resp = client.attr_blocks(index_name, field_name)
        except Exception:
            return None
        remote = {b["id"]: b["checksum"] for b in resp.get("blocks", [])}
        attrs = {}
        for bid in sorted(bid for bid, chk in remote.items()
                          if local.get(bid) != chk):
            try:
                data = client.attr_block_data(index_name, field_name, bid)
            except Exception:
                continue
            attrs.update(data.get("attrs", {}))
        return {"attrs": attrs}


class AntiEntropyMonitor:
    """Periodic anti-entropy loop (reference: monitorAntiEntropy
    server.go:514). Suspended while the cluster is resizing."""

    def __init__(self, syncer, interval=600.0):
        self.syncer = syncer
        self.interval = interval
        self._stop = threading.Event()
        self._thread = None
        # stop() must be able to interrupt an in-flight pass
        syncer.is_closing = self._stop.is_set

    def start(self):
        if self.interval <= 0:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="anti-entropy", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                if self.syncer.cluster.state == "RESIZING":
                    continue  # reference: abort anti-entropy cluster.go:269
                self.syncer.sync_holder()
            except Exception:
                logger.exception("anti-entropy pass failed")

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
