"""Translate-store replication: primary -> replica key streaming.

Reference: holder.go:702-880 (holderTranslateStoreReplicator) with
cluster.go:2019's notion of a single writable node. The FIRST node in
sorted order is the writable primary; every other node marks its stores
read-only and continually pulls new entries from the primary. Creates on
a replica forward to the primary via the store's remote_create hook and
are mirrored locally for read-your-writes (reference:
ErrTranslateStoreReadOnly redirect http/handler.go:518-522).

Unlike the reference's predecessor chain, replicas pull from the primary
directly: mirrored forward-writes can land out of ID order on a replica,
so an intermediate chain hop could permanently skip entries; the
primary's feed is strictly monotonic, which makes advance-to-max-pulled
offsets safe. Offsets are replicator-internal (NOT the store's max_id —
mirrored writes leave holes below it) and reset on restart, so a restart
re-pulls the feed once; force_set is idempotent.
"""

import logging
import threading

logger = logging.getLogger("pilosa_tpu.translate")


class TranslateReplicator:
    def __init__(self, holder, cluster, client_factory, interval=1.0):
        self.holder = holder
        self.cluster = cluster
        self.client_factory = client_factory
        self.interval = interval
        self._stop = threading.Event()
        self._thread = None
        self._offsets = {}  # (index, field) -> last replicated id
        # install on the holder so stores created later are configured
        # at birth (no writable window on replicas)
        holder.translate_configurer = self.configure_store
        for store in holder.translate_stores():
            self.configure_store(store)

    # -- topology ------------------------------------------------------------

    def primary(self):
        """The coordinator is the writable translate primary: it is
        STABLE across joins (a joining node never becomes coordinator
        automatically), its removal is forbidden, and transfer is an
        explicit admin action — so the primary can't silently move to a
        node with an empty key store (which would let fresh allocations
        overwrite existing id->key mappings on replicas)."""
        return self.cluster.coordinator

    def is_replica(self):
        p = self.primary()
        return p is not None and p.id != self.cluster.local_id

    # -- store wiring --------------------------------------------------------

    def configure_store(self, store):
        store.set_read_only(self.is_replica())
        store.remote_create = self._remote_create_fn(store)

    def _remote_create_fn(self, store):
        def create(keys):
            primary = self.primary()
            if primary is None or primary.id == self.cluster.local_id:
                raise RuntimeError(
                    "read-only translate store with no primary to forward to")
            client = self.client_factory(primary.uri)
            resp = client.translate_keys_create(
                store.index, store.field, keys)
            return resp["ids"]
        return create

    def refresh(self):
        """Re-evaluate the chain after a topology change (resize)."""
        for store in self.holder.translate_stores():
            self.configure_store(store)

    # -- replication loop ----------------------------------------------------

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="translate-replicator", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.refresh()
                self.replicate_once()
            except Exception:
                logger.exception("translate replication pass failed")

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self.holder.translate_configurer is self.configure_store:
            self.holder.translate_configurer = None

    def replicate_once(self):
        """Pull new entries for every store from the primary and apply
        them via force_set (reference: replicate() holder.go:837-880).
        Returns entries applied."""
        from .client import ClientError

        if not self.is_replica():
            return 0
        client = self.client_factory(self.primary().uri)
        applied = 0
        for store in self.holder.translate_stores():
            key = (store.index, store.field)
            offset = self._offsets.get(key, 0)
            try:
                resp = client.translate_entries(
                    store.index, store.field, offset=offset)
            except ClientError as e:
                if e.status != 404:  # 404: primary lacks the index yet
                    logger.warning("translate pull %s/%s from primary "
                                   "failed: %s", store.index, store.field, e)
                continue
            except Exception as e:
                logger.warning("translate pull %s/%s from primary "
                               "failed: %s", store.index, store.field, e)
                continue
            for d in resp.get("entries", []):
                old = store.translate_ids([d["id"]])[0]
                if old is not None and old != d["key"]:
                    # should be impossible with a stable primary; scream
                    logger.error(
                        "translate divergence %s/%s id=%d: %r -> %r",
                        store.index, store.field, d["id"], old, d["key"])
                store.force_set(d["id"], d["key"])
                offset = max(offset, d["id"])
                applied += 1
            self._offsets[key] = offset
        return applied
