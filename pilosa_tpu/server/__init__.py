"""Server layer: API facade, HTTP transport, client (reference: api.go,
http/, server/)."""

from .api import API, ApiError, ConflictError, NotFoundError
from .client import Client, ClientError
from .http_server import PilosaHTTPServer
from .syncer import AntiEntropyMonitor, FragmentSyncer, HolderSyncer
from .translate_sync import TranslateReplicator
