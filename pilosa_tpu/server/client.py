"""HTTP client (reference: http/client.go InternalClient).

Used by applications, the CLI import/export commands, and node-to-node
data-plane RPC in the cluster layer. stdlib urllib; no external deps.

Resilience: every request takes an optional per-request deadline, and
idempotent requests (GETs, DELETEs, and the import paths — set-bit and
roaring imports re-apply cleanly, BSI values are last-write-wins) retry
transient failures with bounded, jittered exponential backoff. A 503
with ``Retry-After`` (readiness gating, resize-queue overflow) is always
retryable — the server has explicitly promised the request will work
later — and the advertised delay is honored up to the backoff cap."""

import json
import random
import threading
import time
import urllib.error
import urllib.request

# -- HTTP data-plane byte accounting ------------------------------------
# Response bytes of node-to-node REMOTE query fan-out — the cluster's
# HTTP DATA plane (result payloads), as opposed to control traffic
# (step announcements, validation, health). The SPMD serving bench
# asserts this stays flat while collectives serve: result bytes ride
# the fabric, not HTTP. Process-wide (every Client instance counts).
_data_plane_lock = threading.Lock()
_data_plane_bytes = 0


def _note_data_plane(n):
    global _data_plane_bytes
    with _data_plane_lock:
        _data_plane_bytes += int(n)


def data_plane_bytes():
    with _data_plane_lock:
        return _data_plane_bytes


def reset_data_plane_bytes():
    """Bench/test isolation."""
    global _data_plane_bytes
    with _data_plane_lock:
        _data_plane_bytes = 0


class ClientError(Exception):
    def __init__(self, status, message):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class DeadlineExceeded(ClientError):
    """The per-request deadline expired before a successful response
    (status 0: the failure is client-side, no HTTP status exists)."""

    def __init__(self, message):
        super().__init__(0, message)


class Client:
    def __init__(self, base_url, timeout=30, tls_skip_verify=False,
                 ca_cert=None, retries=2, backoff=0.1, backoff_max=2.0,
                 deadline=None):
        """tls_skip_verify / ca_cert: https trust options (reference:
        tls.skip-verify / tls.ca-certificate server config).

        retries: extra attempts for retryable failures (0 disables);
        backoff/backoff_max: jittered exponential backoff bounds, also
        the cap on an honored ``Retry-After``; deadline: default
        per-request wall-clock budget in seconds across ALL attempts
        (None = no deadline; per-attempt socket timeout still applies)."""
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.retries = retries
        self.backoff = backoff
        self.backoff_max = backoff_max
        self.deadline = deadline
        self._ssl_context = None
        if base_url.startswith("https"):
            import ssl

            if tls_skip_verify:
                ctx = ssl.create_default_context()
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
                self._ssl_context = ctx
            elif ca_cert:
                self._ssl_context = ssl.create_default_context(
                    cafile=ca_cert)

    def _request(self, method, path, body=None,
                 content_type="application/json", idempotent=None,
                 deadline=None, headers=None):
        """idempotent: may network-level failures be retried? (an HTTP
        503 is retried regardless — the server rejected the request
        before doing work). Defaults to True for GET/DELETE.
        headers: extra request headers sent on every attempt (e.g. the
        forwarded X-Request-Deadline on cluster fan-out)."""
        if idempotent is None:
            idempotent = method in ("GET", "DELETE")
        if deadline is None:
            deadline = self.deadline
        deadline_at = None if deadline is None else \
            time.monotonic() + deadline
        attempt = 0
        while True:
            retry_after = None
            try:
                return self._request_once(method, path, body, content_type,
                                          deadline_at, headers)
            except ClientError as e:
                if e.status != 503 or attempt >= self.retries:
                    raise
                retry_after = getattr(e, "retry_after", None)
            except (urllib.error.URLError, TimeoutError, OSError):
                # includes socket.timeout and connection refused/reset;
                # non-idempotent requests may have partially executed
                if not idempotent or attempt >= self.retries:
                    raise
            delay = min(self.backoff_max,
                        self.backoff * (2 ** attempt))
            delay *= random.uniform(0.5, 1.0)  # jitter: decorrelate peers
            if retry_after is not None:
                # the server knows better than our backoff curve, but
                # never wait longer than the configured cap
                delay = min(max(delay, retry_after), self.backoff_max)
            if deadline_at is not None and \
                    time.monotonic() + delay >= deadline_at:
                raise DeadlineExceeded(
                    f"deadline exceeded after {attempt + 1} attempt(s): "
                    f"{method} {path}")
            time.sleep(delay)
            attempt += 1

    def _request_once(self, method, path, body, content_type, deadline_at,
                      headers=None):
        from ..utils import tracing

        timeout = self.timeout
        if deadline_at is not None:
            remaining = deadline_at - time.monotonic()
            if remaining <= 0:
                raise DeadlineExceeded(
                    f"deadline exceeded: {method} {path}")
            timeout = min(timeout, remaining)
        req = urllib.request.Request(
            self.base_url + path, data=body, method=method)
        if body is not None:
            req.add_header("Content-Type", content_type)
        if headers:
            for k, v in headers.items():
                req.add_header(k, v)
        for k, v in tracing.inject_headers().items():
            req.add_header(k, v)  # cross-node trace context (client inject)
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout,
                    context=self._ssl_context) as resp:
                data = resp.read()
                ctype = resp.headers.get("Content-Type", "")
        except urllib.error.HTTPError as e:
            try:
                message = json.loads(e.read().decode()).get("error", str(e))
            except Exception:
                message = str(e)
            err = ClientError(e.code, message)
            ra = e.headers.get("Retry-After") if e.headers else None
            if ra is not None:
                try:
                    err.retry_after = float(ra)
                except ValueError:
                    pass
            # which shedding site rejected us (admission, coalesce,
            # ingest, resize_queue) — lets the cluster layer tell an
            # OVERLOADED peer from an unready/dead one
            shed = e.headers.get("X-Pilosa-Shed") if e.headers else None
            if shed is not None:
                err.shed = shed
            raise err from e
        if "/query" in path and "remote=true" in path:
            # JSON-wire remote fan-out: result bytes over HTTP (the
            # proto wire counts in query_proto, whose path carries no
            # remote param)
            _note_data_plane(len(data))
        if ctype.startswith("application/json"):
            return json.loads(data.decode()) if data else None
        return data

    # -- schema --------------------------------------------------------------

    def create_index(self, name, keys=False, track_existence=True):
        return self._request("POST", f"/index/{name}", json.dumps({
            "options": {"keys": keys, "trackExistence": track_existence},
        }).encode())

    def delete_index(self, name):
        return self._request("DELETE", f"/index/{name}")

    def create_field(self, index, field, options=None):
        return self._request(
            "POST", f"/index/{index}/field/{field}",
            json.dumps({"options": options or {}}).encode())

    def delete_field(self, index, field):
        return self._request("DELETE", f"/index/{index}/field/{field}")

    def schema(self):
        return self._request("GET", "/schema")

    # -- queries -------------------------------------------------------------

    @staticmethod
    def _query_headers(deadline, query_class):
        """X-Request-Deadline / X-Query-Class headers (None when
        neither is set). `deadline` is a RELATIVE budget in seconds —
        the receiving edge re-anchors it against its own clock, so
        coordinator/peer clock skew never corrupts the deadline."""
        headers = {}
        if deadline is not None:
            headers["X-Request-Deadline"] = f"{float(deadline):.6f}"
        if query_class is not None:
            headers["X-Query-Class"] = query_class
        return headers or None

    def query_proto(self, index, pql, shards=None, remote=False,
                    exclude_row_attrs=False, exclude_columns=False,
                    deadline=None, query_class=None):
        """Query over the protobuf data plane (reference:
        InternalClient.QueryNode posts proto QueryRequests). Returns
        (results, err). deadline: remaining budget in seconds, sent as
        X-Request-Deadline AND bounding local retries."""
        from .. import encoding

        body = encoding.encode_query_request(
            pql, shards=shards, remote=remote,
            exclude_row_attrs=exclude_row_attrs,
            exclude_columns=exclude_columns)
        data = self._request(
            "POST", f"/index/{index}/query", body,
            content_type=encoding.CONTENT_TYPE_PROTOBUF,
            deadline=deadline,
            headers=self._query_headers(deadline, query_class))
        if remote and isinstance(data, (bytes, bytearray)):
            _note_data_plane(len(data))
        return encoding.decode_query_response(data)

    def query(self, index, pql, shards=None, remote=False,
              exclude_row_attrs=False, exclude_columns=False,
              profile=False, explain=None, deadline=None,
              query_class=None):
        """(reference: InternalClient.QueryNode http/client.go:268; remote
        marks node-to-node fan-out requests that must not re-fan-out;
        profile asks the server to return the query's span-tree profile
        alongside the results; explain="plan" returns the annotated plan
        WITHOUT executing, explain="analyze" executes and returns the
        plan with actual costs grafted on; deadline: remaining budget in
        seconds, sent as X-Request-Deadline and bounding local retries;
        query_class: admission class forwarded as X-Query-Class)"""
        path = f"/index/{index}/query"
        params = []
        if shards is not None:
            params.append("shards=" + ",".join(str(s) for s in shards))
        if remote:
            params.append("remote=true")
        if exclude_row_attrs:
            params.append("excludeRowAttrs=true")
        if exclude_columns:
            params.append("excludeColumns=true")
        if profile:
            params.append("profile=true")
        if explain:
            params.append(f"explain={explain}")
        if params:
            path += "?" + "&".join(params)
        return self._request(
            "POST", path, pql.encode(), content_type="text/plain",
            deadline=deadline,
            headers=self._query_headers(deadline, query_class))

    # -- imports -------------------------------------------------------------

    def import_bits(self, index, field, row_ids, column_ids,
                    timestamps=None, clear=False, remote=False,
                    row_keys=None, column_keys=None, deadline=None):
        """idempotent=True: re-setting a set bit is a no-op, so a retry
        after an ambiguous network failure cannot corrupt anything."""
        path = f"/index/{index}/field/{field}/import"
        params = []
        if clear:
            params.append("clear=true")
        if remote:
            params.append("remote=true")
        if params:
            path += "?" + "&".join(params)
        body = {}
        if row_keys is not None:
            body["rowKeys"] = list(row_keys)
        else:
            body["rowIDs"] = [int(r) for r in row_ids]
        if column_keys is not None:
            body["columnKeys"] = list(column_keys)
        else:
            body["columnIDs"] = [int(c) for c in column_ids]
        if timestamps is not None:
            body["timestamps"] = timestamps
        return self._request("POST", path, json.dumps(body).encode(),
                             idempotent=True, deadline=deadline)

    def import_values(self, index, field, column_ids, values, remote=False,
                      column_keys=None, clear=False, deadline=None):
        """idempotent=True: replaying the same value assignment is
        last-write-wins over itself."""
        path = f"/index/{index}/field/{field}/import"
        params = [p for p, on in (("remote=true", remote),
                                  ("clear=true", clear)) if on]
        if params:
            path += "?" + "&".join(params)
        body = {"values": [int(v) for v in values]}
        if column_keys is not None:
            body["columnKeys"] = list(column_keys)
        else:
            body["columnIDs"] = [int(c) for c in column_ids]
        return self._request("POST", path, json.dumps(body).encode(),
                             idempotent=True, deadline=deadline)

    def import_roaring(self, index, field, shard, data, clear=False,
                       view="standard", remote=False, deadline=None):
        path = (f"/index/{index}/field/{field}/import-roaring/{shard}"
                f"?view={view}")
        if clear:
            path += "&clear=true"
        if remote:
            path += "&remote=true"
        return self._request(
            "POST", path, data, content_type="application/octet-stream",
            idempotent=True, deadline=deadline)

    # -- misc ----------------------------------------------------------------

    def status(self):
        return self._request("GET", "/status")

    def info(self):
        return self._request("GET", "/info")

    # -- debug / observability -----------------------------------------------

    def debug_hbm(self, top=50):
        """Per-node HBM ledger (coordinator /status aggregation reads
        this from every peer)."""
        return self._request("GET", f"/debug/hbm?top={top}")

    def debug_kernels(self, costs=True):
        """Per-node kernel attribution; costs=False skips the lazy
        cost_analysis compile on the peer."""
        path = "/debug/kernels" + ("" if costs else "?costs=false")
        return self._request("GET", path)

    def debug_plans(self, limit=None):
        """The peer's retained (misestimated) EXPLAIN ANALYZE plans +
        misestimate counters; limit=0 fetches counters only."""
        path = "/debug/plans"
        if limit is not None:
            path += f"?limit={int(limit)}"
        return self._request("GET", path)

    def debug_device(self, limit=None):
        """The peer's device-link health (state machine + canary ring);
        limit=0 fetches the state summary without the ring."""
        path = "/debug/device"
        if limit is not None:
            path += f"?limit={int(limit)}"
        return self._request("GET", path)

    def debug_dispatch(self):
        """The peer's per-kernel dispatch-phase RTT decomposition."""
        return self._request("GET", "/debug/dispatch")

    def debug_oplog(self):
        """The peer's durable-oplog summary (segments, checkpoint,
        replay lag); {"enabled": False} when the node runs without one."""
        return self._request("GET", "/debug/oplog")

    def debug_workload(self, top=None):
        """The peer's per-fingerprint workload table (top-K rankings);
        top=1 fetches the headline entry only."""
        path = "/debug/workload"
        if top is not None:
            path += f"?top={int(top)}"
        return self._request("GET", path)

    def debug_heat(self, top=None):
        """The peer's fragment heat ledger joined against HBM
        residency; top=0 fetches totals without the ranked lists."""
        path = "/debug/heat"
        if top is not None:
            path += f"?top={int(top)}"
        return self._request("GET", path)

    def debug_slo(self):
        """The peer's SLO burn-rate state (objectives, windows,
        alerting flags)."""
        return self._request("GET", "/debug/slo")

    def debug_admission(self):
        """The peer's admission-controller snapshot (ladder state,
        token buckets, queue occupancy); {"enabled": False} when the
        node runs with --admission off."""
        return self._request("GET", "/debug/admission")

    def debug_flightrecorder(self, limit=None):
        """The peer's flight-recorder tail."""
        path = "/debug/flightrecorder"
        if limit is not None:
            path += f"?limit={int(limit)}"
        return self._request("GET", path)

    def debug_trace(self, trace_id, deadline=2.0):
        """The peer's LOCAL finished spans for one trace id (the
        cross-node assembly getter — the coordinator merges these into
        one tree with skew-corrected timestamps). Short default deadline:
        assembly is best-effort garnish on a finished query, never worth
        blocking the response on a slow peer."""
        return self._request(
            "GET", f"/debug/traces/{trace_id}?local=true",
            deadline=deadline)

    def debug_incidents(self):
        """The peer's postmortem-bundle listing ({"enabled": False} when
        the node runs without --incident-dir)."""
        return self._request("GET", "/debug/incidents")

    def debug_spmd(self, deadline=2.0):
        """The peer's SPMD-plane snapshot (serve mode, step-lifecycle
        counters, stream + observatory state); {"enabled": False} when
        the node runs without --spmd. Short deadline: the /status
        observability roll-up must never wedge behind a stalled mesh."""
        return self._request("GET", "/debug/spmd", deadline=deadline)

    def debug_spmd_steps(self, seq=None, limit=None, deadline=2.0):
        """The peer's LOCAL slice of the collective step timeline (step
        ring + per-phase walls, stamped with the peer's wall clock). The
        ?local=true form, same shape as debug_trace: the coordinator
        skew-corrects from the RPC envelope and merges — the fan-out
        cannot recurse."""
        path = "/debug/spmd/steps"
        if seq is not None:
            path += f"/{int(seq)}"
        path += "?local=true"
        if limit is not None:
            path += f"&limit={int(limit)}"
        return self._request("GET", path, deadline=deadline)

    def export_csv(self, index, field, shard):
        data = self._request(
            "GET", f"/export?index={index}&field={field}&shard={shard}")
        return data.decode() if isinstance(data, bytes) else data

    def nodes(self):
        return self._request("GET", "/internal/nodes")

    # -- node-to-node internals (reference: http/client.go internal paths) ---

    def index_shards(self, index):
        """Available shards on this node (reference: availableShards
        gossip; here an internal endpoint)."""
        return self._request("GET", f"/internal/index/{index}/shards")

    def spmd_step(self, step):
        """Announce an SPMD collective step (control plane; the result
        bytes themselves merge over the accelerator fabric)."""
        import json as _json

        return self._request(
            "POST", "/internal/spmd/step", _json.dumps(step).encode(),
            content_type="application/json")

    def spmd_stream(self, step):
        """Announce a STREAMED SPMD step (serve-mode on): the peer
        enqueues by sequence number and acks immediately — the ack does
        not wait for the collective, which is what lets the coordinator
        pipeline announcement N+1 while step N executes."""
        import json as _json

        return self._request(
            "POST", "/internal/spmd/stream", _json.dumps(step).encode(),
            content_type="application/json")

    def spmd_validate(self, step):
        """Pre-flight an SPMD step (cheap, short-deadline)."""
        import json as _json

        return self._request(
            "POST", "/internal/spmd/validate", _json.dumps(step).encode(),
            content_type="application/json")

    def spmd_initiate(self, payload):
        """Forward an eligible call to the coordinator for collective step
        initiation (non-coordinator one-hop path)."""
        import json as _json

        return self._request(
            "POST", "/internal/spmd/initiate", _json.dumps(payload).encode(),
            content_type="application/json")

    def shard_fragments(self, index, shard):
        """(field, view) fragments a node holds for one shard (resize
        streaming discovery)."""
        return self._request(
            "GET", f"/internal/index/{index}/shard/{shard}/fragments")

    def send_message(self, data):
        """POST a control-plane message (reference: SendMessage
        http/client.go:1017 -> /internal/cluster/message)."""
        return self._request(
            "POST", "/internal/cluster/message", data,
            content_type="application/octet-stream")

    def fragment_blocks(self, index, field, view, shard):
        """(reference: /internal/fragment/blocks handler.go:300)"""
        return self._request(
            "GET", f"/internal/fragment/blocks?index={index}&field={field}"
                   f"&view={view}&shard={shard}")

    def fragment_block_data(self, index, field, view, shard, block):
        """(reference: /internal/fragment/block/data)"""
        return self._request(
            "GET", f"/internal/fragment/block/data?index={index}"
                   f"&field={field}&view={view}&shard={shard}&block={block}")

    def fragment_data(self, index, field, view, shard):
        """Whole serialized fragment (reference: /internal/fragment/data,
        used by resize streaming http/client.go:742)."""
        return self._request(
            "GET", f"/internal/fragment/data?index={index}&field={field}"
                   f"&view={view}&shard={shard}")

    def translate_entries(self, index, field="", offset=0):
        """Translate-store replication feed (reference: /internal/translate/
        data holder.go:702-880)."""
        return self._request(
            "GET", f"/internal/translate/data?index={index}&field={field}"
                   f"&offset={offset}")

    # -- resize admin (reference: /cluster/resize/* api.go:1193-1267) --------

    def resize_add_node(self, node_id, uri):
        return self._request(
            "POST", "/cluster/resize/add-node",
            json.dumps({"id": node_id, "uri": uri}).encode())

    def resize_remove_node(self, node_id):
        return self._request(
            "POST", "/cluster/resize/remove-node",
            json.dumps({"id": node_id}).encode())

    def resize_abort(self):
        return self._request("POST", "/cluster/resize/abort", b"{}")

    def resize_status(self):
        return self._request("GET", "/cluster/resize/status")

    def set_coordinator(self, node_id):
        return self._request(
            "POST", "/cluster/resize/set-coordinator",
            json.dumps({"id": node_id}).encode())

    def translate_keys_create(self, index, field, keys):
        """Allocate key ids on the primary (reference: translate key
        writes route to primary http/handler.go:518-522)."""
        return self._request(
            "POST", "/internal/translate/keys",
            json.dumps({"index": index, "field": field,
                        "keys": list(keys)}).encode())

    def attr_blocks(self, index, field=""):
        """(reference: attr diff endpoints api.go:817-891)"""
        return self._request(
            "GET", f"/internal/attr/blocks?index={index}&field={field}")

    def attr_block_data(self, index, field="", block=0):
        return self._request(
            "GET", f"/internal/attr/data?index={index}&field={field}"
                   f"&block={block}")

    def attr_diff(self, index, blocks, field=""):
        """Post local block checksums, receive attrs from every block the
        peer has that differs (reference: handler.go:312,315)."""
        path = f"/internal/index/{index}/attr/diff" if not field else \
            f"/internal/index/{index}/field/{field}/attr/diff"
        return self._request(
            "POST", path, json.dumps({"blocks": blocks}).encode())
