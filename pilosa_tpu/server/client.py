"""HTTP client (reference: http/client.go InternalClient).

Used by applications, the CLI import/export commands, and node-to-node
data-plane RPC in the cluster layer. stdlib urllib; no external deps."""

import json
import urllib.error
import urllib.request


class ClientError(Exception):
    def __init__(self, status, message):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class Client:
    def __init__(self, base_url, timeout=30):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _request(self, method, path, body=None, content_type="application/json"):
        req = urllib.request.Request(
            self.base_url + path, data=body, method=method)
        if body is not None:
            req.add_header("Content-Type", content_type)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                data = resp.read()
                ctype = resp.headers.get("Content-Type", "")
        except urllib.error.HTTPError as e:
            try:
                message = json.loads(e.read().decode()).get("error", str(e))
            except Exception:
                message = str(e)
            raise ClientError(e.code, message) from e
        if ctype.startswith("application/json"):
            return json.loads(data.decode()) if data else None
        return data

    # -- schema --------------------------------------------------------------

    def create_index(self, name, keys=False, track_existence=True):
        return self._request("POST", f"/index/{name}", json.dumps({
            "options": {"keys": keys, "trackExistence": track_existence},
        }).encode())

    def delete_index(self, name):
        return self._request("DELETE", f"/index/{name}")

    def create_field(self, index, field, options=None):
        return self._request(
            "POST", f"/index/{index}/field/{field}",
            json.dumps({"options": options or {}}).encode())

    def delete_field(self, index, field):
        return self._request("DELETE", f"/index/{index}/field/{field}")

    def schema(self):
        return self._request("GET", "/schema")

    # -- queries -------------------------------------------------------------

    def query(self, index, pql, shards=None):
        """(reference: InternalClient.QueryNode http/client.go:268)"""
        path = f"/index/{index}/query"
        if shards is not None:
            path += "?shards=" + ",".join(str(s) for s in shards)
        return self._request(
            "POST", path, pql.encode(), content_type="text/plain")

    # -- imports -------------------------------------------------------------

    def import_bits(self, index, field, row_ids, column_ids,
                    timestamps=None, clear=False):
        path = f"/index/{index}/field/{field}/import"
        if clear:
            path += "?clear=true"
        body = {"rowIDs": [int(r) for r in row_ids],
                "columnIDs": [int(c) for c in column_ids]}
        if timestamps is not None:
            body["timestamps"] = timestamps
        return self._request("POST", path, json.dumps(body).encode())

    def import_values(self, index, field, column_ids, values):
        path = f"/index/{index}/field/{field}/import"
        body = {"columnIDs": [int(c) for c in column_ids],
                "values": [int(v) for v in values]}
        return self._request("POST", path, json.dumps(body).encode())

    def import_roaring(self, index, field, shard, data, clear=False,
                       view="standard"):
        path = (f"/index/{index}/field/{field}/import-roaring/{shard}"
                f"?view={view}")
        if clear:
            path += "&clear=true"
        return self._request(
            "POST", path, data, content_type="application/octet-stream")

    # -- misc ----------------------------------------------------------------

    def status(self):
        return self._request("GET", "/status")

    def info(self):
        return self._request("GET", "/info")

    def export_csv(self, index, field, shard):
        data = self._request(
            "GET", f"/export?index={index}&field={field}&shard={shard}")
        return data.decode() if isinstance(data, bytes) else data

    def nodes(self):
        return self._request("GET", "/internal/nodes")
