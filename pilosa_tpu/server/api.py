"""API facade (reference: api.go).

Sits between transports (HTTP, cluster-internal RPC) and the
holder/executor. Validation of cluster-state-permitted methods
(reference: api.validate api.go:119) hooks in once the cluster layer is
attached; single-node mode permits everything.
"""

import io
import csv

import numpy as np

from ..core import FieldOptions, Holder, IndexOptions
from ..core.field import (
    FIELD_TYPE_BOOL,
    FIELD_TYPE_INT,
    FIELD_TYPE_MUTEX,
    FIELD_TYPE_SET,
    FIELD_TYPE_TIME,
)
from ..exec import ExecOptions, Executor
from ..pql import parse
from ..shardwidth import SHARD_WIDTH
from .. import __version__


class ApiError(Exception):
    status = 400


class NotFoundError(ApiError):
    status = 404


class ConflictError(ApiError):
    status = 409


def field_options_from_json(opts):
    """Build FieldOptions from the reference's JSON field-options wire shape
    (reference: fieldOptions handler struct http/handler.go:870 +
    FieldOptions.MarshalJSON field.go:1471)."""
    opts = opts or {}
    typ = opts.get("type", FIELD_TYPE_SET)
    if typ == FIELD_TYPE_INT:
        return FieldOptions.int_field(
            min=int(opts.get("min", -(1 << 31))),
            max=int(opts.get("max", (1 << 31) - 1)))
    if typ == FIELD_TYPE_TIME:
        return FieldOptions.time_field(
            opts.get("timeQuantum", ""),
            no_standard_view=bool(opts.get("noStandardView", False)),
            keys=bool(opts.get("keys", False)))
    if typ == FIELD_TYPE_MUTEX:
        return FieldOptions.mutex_field(
            cache_type=opts.get("cacheType", "ranked"),
            cache_size=int(opts.get("cacheSize", 50000)),
            keys=bool(opts.get("keys", False)))
    if typ == FIELD_TYPE_BOOL:
        return FieldOptions.bool_field()
    if typ != FIELD_TYPE_SET:
        raise ApiError(f"invalid field type: {typ}")
    return FieldOptions(
        cache_type=opts.get("cacheType", "ranked"),
        cache_size=int(opts.get("cacheSize", 50000)),
        keys=bool(opts.get("keys", False)))


def field_options_to_json(o):
    out = {"type": o.type, "keys": o.keys}
    if o.type == FIELD_TYPE_INT:
        out.update({"min": o.min, "max": o.max, "base": o.base,
                    "bitDepth": o.bit_depth})
    elif o.type == FIELD_TYPE_TIME:
        out.update({"timeQuantum": o.time_quantum,
                    "noStandardView": o.no_standard_view})
    else:
        out.update({"cacheType": o.cache_type, "cacheSize": o.cache_size})
    return out


def result_to_json(result):
    """Encode one executor result in the reference's QueryResponse JSON
    shape (reference: QueryResponse.MarshalJSON handler.go:61,
    Row.MarshalJSON row.go:303)."""
    from ..core.row import Row
    from ..exec.result import GroupCount, Pair, RowIdentifiers, ValCount

    if isinstance(result, Row):
        out = {"attrs": result.attrs or {},
               "columns": [int(c) for c in result.columns()]}
        if result.keys is not None:
            out["keys"] = result.keys
        return out
    if isinstance(result, list):
        return [result_to_json(r) for r in result]
    if isinstance(result, (ValCount, Pair, RowIdentifiers, GroupCount)):
        return result.to_json()
    if result is None or isinstance(result, (bool, int, float, str, dict)):
        return result
    raise ApiError(f"unencodable result type {type(result)!r}")


class API:
    def __init__(self, holder, cluster=None):
        self.holder = holder
        self.cluster = cluster
        self.executor = Executor(holder)

    # -- queries ------------------------------------------------------------

    def query(self, index_name, pql, shards=None, options=None):
        """(reference: api.Query api.go:135)"""
        if self.holder.index(index_name) is None:
            raise NotFoundError(f"index not found: {index_name}")
        try:
            query = parse(pql) if isinstance(pql, str) else pql
            results = self.executor.execute(
                index_name, query, shards=shards, options=options)
        except (ApiError,):
            raise
        except Exception as e:
            raise ApiError(str(e)) from e
        return results

    # -- schema DDL ---------------------------------------------------------

    def create_index(self, name, options=None):
        from ..core.holder import HolderError
        from ..core.index import IndexError_

        try:
            idx = self.holder.create_index(name, options=options)
        except HolderError as e:
            raise ConflictError(str(e)) from e
        except IndexError_ as e:
            raise ApiError(str(e)) from e
        self._broadcast_schema()
        return idx

    def delete_index(self, name):
        from ..core.holder import HolderError

        try:
            self.holder.delete_index(name)
        except HolderError as e:
            raise NotFoundError(str(e)) from e
        self._broadcast_schema()

    def create_field(self, index_name, field_name, options=None):
        from ..core.index import IndexError_

        idx = self.holder.index(index_name)
        if idx is None:
            raise NotFoundError(f"index not found: {index_name}")
        try:
            field = idx.create_field(field_name, options=options)
        except IndexError_ as e:
            if "already exists" in str(e):
                raise ConflictError(str(e)) from e
            raise ApiError(str(e)) from e
        self._broadcast_schema()
        return field

    def delete_field(self, index_name, field_name):
        from ..core.index import IndexError_

        idx = self.holder.index(index_name)
        if idx is None:
            raise NotFoundError(f"index not found: {index_name}")
        try:
            idx.delete_field(field_name)
        except IndexError_ as e:
            raise NotFoundError(str(e)) from e
        self._broadcast_schema()

    def schema(self):
        """Public schema in the reference's camelCase wire shape
        (reference: handleGetSchema + FieldOptions.MarshalJSON)."""
        out = []
        for iname in sorted(self.holder.indexes):
            idx = self.holder.indexes[iname]
            fields = []
            for fname in sorted(idx.public_fields()):
                f = idx.fields[fname]
                fields.append({
                    "name": fname,
                    "options": field_options_to_json(f.options),
                    "shards": f.available_shards(),
                })
            out.append({
                "name": iname,
                "options": {"keys": idx.options.keys,
                            "trackExistence": idx.options.track_existence},
                "fields": fields,
            })
        return {"indexes": out}

    def apply_schema(self, schema):
        """Accepts the camelCase wire shape (reference: handlePostSchema)."""
        for idx_desc in schema.get("indexes", []):
            opts = idx_desc.get("options", {})
            idx = self.holder.create_index(
                idx_desc["name"],
                options=IndexOptions(
                    keys=bool(opts.get("keys", False)),
                    track_existence=bool(opts.get("trackExistence", True))),
                if_not_exists=True)
            for f_desc in idx_desc.get("fields", []):
                idx.create_field(
                    f_desc["name"],
                    options=field_options_from_json(f_desc.get("options")),
                    if_not_exists=True)

    def _broadcast_schema(self):
        if self.cluster is not None:
            self.cluster.broadcast_schema(self.holder.schema())

    # -- imports ------------------------------------------------------------

    def import_bits(self, index_name, field_name, row_ids, column_ids,
                    timestamps=None, clear=False):
        """(reference: api.Import api.go:920)"""
        field = self._field(index_name, field_name)
        changed = field.import_bits(
            row_ids, column_ids, timestamps=timestamps, clear=clear)
        self.holder.index(index_name).add_existence(column_ids)
        return changed

    def import_values(self, index_name, field_name, column_ids, values):
        field = self._field(index_name, field_name)
        changed = field.import_values(column_ids, values)
        self.holder.index(index_name).add_existence(column_ids)
        return changed

    def import_roaring(self, index_name, field_name, shard, data,
                       clear=False, view="standard"):
        """(reference: api.ImportRoaring api.go:368 — fastest ingest)"""
        field = self._field(index_name, field_name)
        v = field.create_view_if_not_exists(view)
        frag = v.create_fragment_if_not_exists(int(shard))
        return frag.import_roaring(data, clear=clear)

    def _field(self, index_name, field_name):
        idx = self.holder.index(index_name)
        if idx is None:
            raise NotFoundError(f"index not found: {index_name}")
        field = idx.field(field_name)
        if field is None:
            raise NotFoundError(f"field not found: {field_name}")
        return field

    # -- export -------------------------------------------------------------

    def export_csv(self, index_name, field_name, shard):
        """(reference: api.ExportCSV api.go:500) row,col lines for one
        shard."""
        field = self._field(index_name, field_name)
        view = field.view()
        frag = view.fragment(int(shard)) if view else None
        buf = io.StringIO()
        writer = csv.writer(buf)
        if frag is not None:
            for row_id in frag.row_ids():
                for col in frag.row_columns(row_id):
                    writer.writerow([row_id, int(col)])
        return buf.getvalue()

    # -- info/status --------------------------------------------------------

    def info(self):
        return {"shardWidth": SHARD_WIDTH, "version": __version__}

    def status(self):
        state = "NORMAL"
        nodes = []
        if self.cluster is not None:
            state = self.cluster.state
            nodes = self.cluster.nodes_json()
        else:
            nodes = [{"id": "local", "uri": {"scheme": "http"},
                      "isCoordinator": True, "state": "READY"}]
        return {"state": state, "nodes": nodes,
                "localShardWidth": SHARD_WIDTH}

    def shards_max(self):
        out = {}
        for name, idx in self.holder.indexes.items():
            shards = idx.available_shards()
            out[name] = shards[-1] if shards else 0
        return {"standard": out}

    def recalculate_caches(self):
        """(reference: api.RecalculateCaches api.go)"""
        self.holder.recalculate_caches()
        return None

    def hosts(self):
        if self.cluster is not None:
            return self.cluster.nodes_json()
        return [{"id": "local", "isCoordinator": True}]
