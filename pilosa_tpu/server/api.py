"""API facade (reference: api.go).

Sits between transports (HTTP, cluster-internal RPC) and the
holder/executor. Validation of cluster-state-permitted methods
(reference: api.validate api.go:119) hooks in once the cluster layer is
attached; single-node mode permits everything.
"""

import array
import base64
import io
import csv
import random
import threading
import time

import numpy as np

from ..cluster.broadcast import MessageType, Serializer
from ..utils import faultpoints
from ..utils import incident as incident_mod
from ..core import FieldOptions, Holder, IndexOptions
from ..core.field import (
    FIELD_TYPE_BOOL,
    FIELD_TYPE_INT,
    FIELD_TYPE_MUTEX,
    FIELD_TYPE_SET,
    FIELD_TYPE_TIME,
)
from ..exec import ExecOptions, Executor
from ..pql import parse
from ..shardwidth import SHARD_WIDTH
from .. import __version__


class ApiError(Exception):
    status = 400
    #: optional extra response headers ({name: value}) — the HTTP layer
    #: emits them verbatim (e.g. Retry-After on 503)
    headers = None


class NotFoundError(ApiError):
    status = 404


class ConflictError(ApiError):
    status = 409


class ServiceUnavailableError(ApiError):
    """503: the node cannot serve right now (device link DOWN). Carries
    Retry-After so clients back off for one probe interval — by then the
    state machine has fresh canary evidence either way."""
    status = 503

    def __init__(self, message, retry_after=None):
        super().__init__(message)
        if retry_after is not None:
            self.headers = {
                "Retry-After": str(max(1, int(round(retry_after))))}


class GatewayTimeoutError(ApiError):
    """504: the request's `X-Request-Deadline` lapsed before (or
    between) dispatches — the work was dropped, never executed, so the
    client should treat it as not-done rather than ambiguous."""
    status = 504


def shed_reject(site, message, retry_after, qclass=None):
    """THE 503 rejection path for every load-shedding site — coalescer
    overflow, ingest back-pressure, resize-queue overflow, admission.
    One shared `rejections_total{site,class}` counter, one jitter rule
    (x1.0-1.25, so a thundering herd of synchronized client retries
    decorrelates — the same reason server/client.py jitters its
    backoff), and the `X-Pilosa-Shed` marker header that lets a
    cluster coordinator tell a *shedding* peer from a *dead* one
    (cluster/executor.py honors it with a same-replica retry instead
    of the node_unready path)."""
    from ..utils.stats import global_stats

    global_stats.count("rejections_total", 1,
                       {"site": site, "class": qclass or "none"})
    ra = max(1.0, float(retry_after)) * random.uniform(1.0, 1.25)
    err = ServiceUnavailableError(message, retry_after=ra)
    err.headers["X-Pilosa-Shed"] = site
    raise err


#: oplog binary-list type codes -> array.array typecodes ('I' is only
#: u4 where the platform says so; the log is node-local, so the machine
#: that wrote a record is the machine that replays it)
_OPLOG_DT = {"u4": "I", "u8": "Q", "i8": "q"}
_U4_OK = array.array("I").itemsize == 4


def _oplog_pack_ints(v):
    """base64-of-packed-ints record field for an id/value list, or None
    when ``v`` isn't an int list (keys, mixed). Tries u4 first — the
    common case for row ids and per-shard column ids — then i8;
    ndarrays pack through numpy without a Python-object round trip."""
    if isinstance(v, np.ndarray):
        if v.dtype.kind == "u":
            b = np.ascontiguousarray(v, dtype="<u8").tobytes()
            return {"__b": base64.b64encode(b).decode(), "dt": "u8"}
        if v.dtype.kind == "i":
            b = np.ascontiguousarray(v, dtype="<i8").tobytes()
            return {"__b": base64.b64encode(b).decode(), "dt": "i8"}
        return None
    if _U4_OK:
        try:
            b = array.array("I", v).tobytes()
            return {"__b": base64.b64encode(b).decode(), "dt": "u4"}
        except (OverflowError, TypeError, ValueError):
            pass
    try:
        b = array.array("q", v).tobytes()
        return {"__b": base64.b64encode(b).decode(), "dt": "i8"}
    except (OverflowError, TypeError, ValueError):
        return None


def field_options_from_json(opts):
    """Build FieldOptions from the reference's JSON field-options wire shape
    (reference: fieldOptions handler struct http/handler.go:870 +
    FieldOptions.MarshalJSON field.go:1471)."""
    opts = opts or {}
    typ = opts.get("type", FIELD_TYPE_SET)
    if typ == FIELD_TYPE_INT:
        return FieldOptions.int_field(
            min=int(opts.get("min", -(1 << 31))),
            max=int(opts.get("max", (1 << 31) - 1)))
    if typ == FIELD_TYPE_TIME:
        return FieldOptions.time_field(
            opts.get("timeQuantum", ""),
            no_standard_view=bool(opts.get("noStandardView", False)),
            keys=bool(opts.get("keys", False)))
    if typ == FIELD_TYPE_MUTEX:
        return FieldOptions.mutex_field(
            cache_type=opts.get("cacheType", "ranked"),
            cache_size=int(opts.get("cacheSize", 50000)),
            keys=bool(opts.get("keys", False)))
    if typ == FIELD_TYPE_BOOL:
        return FieldOptions.bool_field()
    if typ != FIELD_TYPE_SET:
        raise ApiError(f"invalid field type: {typ}")
    return FieldOptions(
        cache_type=opts.get("cacheType", "ranked"),
        cache_size=int(opts.get("cacheSize", 50000)),
        keys=bool(opts.get("keys", False)))


def field_options_to_json(o):
    out = {"type": o.type, "keys": o.keys}
    if o.type == FIELD_TYPE_INT:
        out.update({"min": o.min, "max": o.max, "base": o.base,
                    "bitDepth": o.bit_depth})
    elif o.type == FIELD_TYPE_TIME:
        out.update({"timeQuantum": o.time_quantum,
                    "noStandardView": o.no_standard_view})
    else:
        out.update({"cacheType": o.cache_type, "cacheSize": o.cache_size})
    return out


def result_to_json(result):
    """Encode one executor result in the reference's QueryResponse JSON
    shape (reference: QueryResponse.MarshalJSON handler.go:61,
    Row.MarshalJSON row.go:303)."""
    from ..core.row import Row
    from ..exec.result import GroupCount, Pair, RowIdentifiers, ValCount

    if isinstance(result, Row):
        out = {"attrs": result.attrs or {},
               "columns": [int(c) for c in result.columns()]}
        if result.keys is not None:
            out["keys"] = result.keys
        return out
    if isinstance(result, list):
        return [result_to_json(r) for r in result]
    if isinstance(result, (ValCount, Pair, RowIdentifiers, GroupCount)):
        return result.to_json()
    if result is None or isinstance(result, (bool, int, float, str, dict)):
        return result
    raise ApiError(f"unencodable result type {type(result)!r}")


class QueryCoalescer:
    """Folds concurrent batchable queries into fused vmapped dispatches
    (exec/stacked.launch_query_batch) so the per-dispatch RTT is paid
    once per batch instead of once per query — BENCH_r03 measured
    64.9ms of a 66.1ms p50 sitting in dispatch round-trip.

    Lifecycle: HTTP handler threads submit() parsed single-call queries
    and block on a per-query event; one lazy-started daemon drain thread
    owns the pipeline. On an idle→busy transition it holds the batch
    open for `window` seconds so batchmates arriving within the window
    fuse; while the pipeline is busy the launch+resolve of the previous
    batch IS the accumulation window (no extra sleep). The loop is
    double-buffered: batch N+1 is launched (device enqueue via
    Executor.launch_batch) BEFORE batch N's results are transferred
    back (resolve_batch), so host sync of batch N overlaps device
    execution of batch N+1.

    Overload: a queue past `max_queue` rejects with 503 + Retry-After
    (ServiceUnavailableError headers path) and counts
    batch_rejected_total — never an unbounded wait."""

    def __init__(self, api, window, max_queue=256):
        self.api = api
        self.window = float(window)
        self.max_queue = int(max_queue)
        self._cond = threading.Condition()
        self._queue = []  # member dicts, FIFO
        self._thread = None
        self._closed = False
        # observability (GET /debug/batching)
        self.batches = 0            # fused launches issued
        self.coalesced = 0          # queries that rode a fused launch
        self.rejected = 0           # overload 503s
        self.max_occupancy = 0      # largest single batch seen
        self.batch_hist = {}        # occupancy -> count

    def submit(self, index_name, query, pql):
        """Enqueue one parsed batchable query and wait for its slot of
        the fused result. Returns (results, batch_size, fingerprint);
        re-raises the member's own error (per-query isolation — a
        batchmate's failure is not ours)."""
        from ..utils.stats import global_stats

        m = {"index": index_name, "query": query, "pql": pql,
             "event": threading.Event(), "t0": time.monotonic(),
             "results": None, "error": None, "batch": 0, "fp": None}
        with self._cond:
            if self._closed:
                raise ServiceUnavailableError(
                    "query coalescer shut down", retry_after=1)
            if len(self._queue) >= self.max_queue:
                self.rejected += 1
                global_stats.count("batch_rejected_total", 1)
                shed_reject(
                    "coalesce",
                    f"coalesce queue full ({self.max_queue}); shed load "
                    "or raise --coalesce-max-queue", 1,
                    qclass="interactive")
            self._queue.append(m)
            if self._thread is None:
                self._start_thread_locked()
            self._cond.notify()
        # Bounded waits + a liveness check: the drain loop delivers
        # every member's event even on internal errors (its whole body
        # is exception-guarded), but if the thread is ever lost anyway,
        # fail this handler fast instead of blocking it forever, and
        # leave the coalescer usable for the next submit.
        while not m["event"].wait(0.5):
            with self._cond:
                t = self._thread
                if t is not None and t.is_alive():
                    continue
                if m in self._queue:
                    self._queue.remove(m)
                self._thread = None
                if self._queue and not self._closed:
                    self._start_thread_locked()
            if not m["event"].is_set():
                m["error"] = ServiceUnavailableError(
                    "coalescer drain thread died; retry", retry_after=1)
            break
        if m["error"] is not None:
            raise m["error"]
        return m["results"], m["batch"], m["fp"]

    def _start_thread_locked(self):
        self._thread = threading.Thread(
            target=self._drain_loop, daemon=True, name="query-coalescer")
        self._thread.start()

    def close(self):
        """Shut down the pipeline: wake the drain thread, deliver
        in-flight batches, fail queued members with 503 so blocked
        handler threads return instead of hanging past server shutdown,
        and refuse new submits. Idempotent."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            t = self._thread
            self._cond.notify_all()
        if t is not None and t.is_alive():
            t.join(timeout=5)
        # no drain thread (never started, or already dead): fail the
        # leftovers here; otherwise the loop's shutdown path did it
        self._fail(self._pop_members(), ServiceUnavailableError(
            "query coalescer shut down", retry_after=1))

    @staticmethod
    def _fail(members, exc):
        for m in members:
            if not m["event"].is_set():
                m["error"] = exc
                m["event"].set()

    def stats(self):
        with self._cond:
            return {
                "enabled": True,
                "window_seconds": self.window,
                "max_queue": self.max_queue,
                "queue_depth": len(self._queue),
                "batches": self.batches,
                "coalesced_queries": self.coalesced,
                "rejected": self.rejected,
                "max_occupancy": self.max_occupancy,
                "occupancy_hist": dict(sorted(self.batch_hist.items())),
            }

    def _pop_members(self):
        """Drain everything queued right now (caller holds no lock)."""
        with self._cond:
            members, self._queue = self._queue, []
            return members

    def _drain_loop(self):
        from ..utils import flightrec
        from ..utils.stats import global_stats

        ex = self.api.batch_executor()
        pending = []  # [(handle, state, members)] launched, unresolved
        while True:
            idle = False
            with self._cond:
                idle = not self._queue and not pending and not self._closed
            if idle:
                # idle dispatch-lock window: bounded proactive admission
                # of hot_but_not_resident fragments (exec/adaptive) —
                # exception-guarded and a no-op with the engine off, so
                # serving can never wedge on an admission failure
                try:
                    admit = getattr(ex, "maybe_proactive_admit", None)
                    if admit is not None:
                        admit()
                except Exception:  # noqa: BLE001 — observability only
                    pass
            with self._cond:
                while not self._queue and not pending \
                        and not self._closed:
                    self._cond.wait()
                if self._closed:
                    break
            # Everything below is exception-guarded: an error ANYWHERE
            # in the iteration (stats, flightrec, grouping — not just
            # the launch/resolve calls, which guard themselves) is
            # delivered to every affected member and the loop keeps
            # serving. An unguarded escape here used to kill the
            # singleton thread and wedge all future submits forever.
            members = []
            launched = []
            try:
                was_idle = not pending
                members = self._pop_members()
                if members and was_idle and self.window > 0:
                    # idle→busy: hold the window open so concurrent
                    # arrivals fuse into this batch (busy pipelines get
                    # their window for free from the previous resolve);
                    # close() cuts the wait short
                    with self._cond:
                        self._cond.wait_for(lambda: self._closed,
                                            timeout=self.window)
                    members += self._pop_members()
                for index_name, group in self._group(members).items():
                    now = time.monotonic()
                    for m in group:
                        global_stats.timing(
                            "coalesce_wait_seconds", now - m["t0"])
                    try:
                        handle, state = ex.launch_batch(
                            index_name, [m["query"] for m in group])
                    except Exception as exc:  # noqa: BLE001 — deliver
                        self._fail(group, exc)
                        continue
                    with self._cond:
                        self.batches += 1
                        self.coalesced += len(group)
                        n = len(group)
                        self.max_occupancy = max(self.max_occupancy, n)
                        self.batch_hist[n] = self.batch_hist.get(n, 0) + 1
                    flightrec.record("batch.coalesce", index=index_name,
                                     queries=len(group))
                    launched.append((handle, state, group))
                # double buffer: batch N+1 is in flight; NOW sync batch N
                for handle, state, group in pending:
                    self._resolve(ex, handle, state, group)
                pending = launched
            except Exception as exc:  # noqa: BLE001 — deliver, don't die
                self._fail(members, exc)
                for _, _, group in pending + launched:
                    self._fail(group, exc)
                pending = []
        # closed: deliver in-flight batches (already launched — the
        # results are real), then fail whatever is still queued
        for handle, state, group in pending:
            self._resolve(ex, handle, state, group)
        self._fail(self._pop_members(), ServiceUnavailableError(
            "query coalescer shut down", retry_after=1))

    def _group(self, members):
        by_index = {}
        for m in members:
            by_index.setdefault(m["index"], []).append(m)
        return by_index

    def _resolve(self, ex, handle, state, group):
        try:
            outs = ex.resolve_batch(handle, state)
        except Exception as exc:  # noqa: BLE001 — deliver, don't die
            for m in group:
                m["error"] = exc
                m["event"].set()
            return
        for m, (results, error, bsize, fp) in zip(group, outs):
            m["results"] = results
            m["error"] = error
            m["batch"] = bsize
            m["fp"] = fp
            m["event"].set()


class API:
    def __init__(self, holder, cluster=None, client_factory=None,
                 long_query_time=None, logger=None, spmd=None,
                 max_writes_per_request=0, oplog=None,
                 coalesce_window=0.0, coalesce_max_queue=256,
                 ingest_interval=0.0, ingest_max_rows=None,
                 ingest_max_bytes=None, admission="off",
                 admission_capacity=None, admission_queue_depth=None,
                 admission_queue_timeout=None):
        from ..cluster import ClusterExecutor
        from ..utils.logger import StandardLogger

        self.holder = holder
        self.cluster = cluster
        # Durable write-ahead oplog (storage/oplog.py): when set, every
        # import appends its record BEFORE any ack path can return, and
        # replay_oplog() re-applies unapplied records at boot. None (the
        # default, and what in-process test harnesses use) keeps the
        # pre-oplog behavior exactly.
        self.oplog = oplog
        # replay_lsn: the original record's LSN while a boot replay is
        # re-running an import through the normal path (no re-append —
        # the record already stands; apply-marking reuses its LSN)
        self._oplog_tls = threading.local()
        self._oplog_ckpt_lock = threading.Lock()
        if oplog is not None:
            oplog.on_rotate = self._oplog_rotate_checkpoint
        # SPMD data plane (cluster/spmd.py): when set, coverable Count
        # merges ride collectives instead of the HTTP data plane.
        self.spmd = spmd
        # Slow-query threshold in seconds (reference: LongQueryTime
        # api.go:1157); None disables the log.
        self.long_query_time = long_query_time
        self.logger = logger if logger is not None else StandardLogger()
        # last per-index shard set pushed to peers (gossiped shard map)
        self._pushed_shards = {}
        if client_factory is None:
            from .client import Client as client_factory  # noqa: N813
        self.client_factory = client_factory
        if cluster is not None:
            from ..cluster import ResizeManager

            self.executor = ClusterExecutor(
                holder, cluster, client_factory, spmd=spmd,
                logger=self.logger,
                max_writes_per_request=max_writes_per_request)
            if spmd is not None:
                # share the serving executor for SPMD condition-leaf
                # evaluation instead of building a second evaluator
                spmd._local_exec = self.executor.local
            self.resize = ResizeManager(holder, cluster, self.client_factory)
            # Writes arriving while RESIZING are queued and replayed once
            # the cluster returns to NORMAL (see import_bits); the resize
            # manager pings us at every RESIZING->NORMAL transition,
            # including on followers and aborts.
            self.resize.on_state_normal = self._drain_resize_writes
        else:
            self.executor = Executor(
                holder, max_writes_per_request=max_writes_per_request)
            self.resize = None
        # Query coalescer (batched dispatch pipeline): window 0 — the
        # default — disables it entirely and keeps the legacy per-query
        # path bit-identical. Cluster coordinators coalesce only when
        # the SPMD mesh serves (serve-mode != off): eligible batches
        # then execute as ONE collective step (SpmdBatchRunner); on the
        # legacy HTTP fan-out path the legs are where dispatches happen,
        # so coordinator coalescing would only add latency.
        self.coalesce_window = float(coalesce_window or 0.0)
        self.coalesce_max_queue = int(coalesce_max_queue)
        if self.coalesce_window > 0 and (
                cluster is None
                or (spmd is not None
                    and getattr(spmd, "serve_mode", "off") != "off")):
            self._coalescer = QueryCoalescer(
                self, self.coalesce_window, self.coalesce_max_queue)
        else:
            self._coalescer = None
        # Streaming ingest engine (exec/ingest.py): interval 0 — the
        # default — never constructs one, so the import path is a single
        # `is None` check and stays byte-identical to the legacy
        # per-import invalidation.
        self.ingest = None
        if float(ingest_interval or 0.0) > 0:
            from ..exec.ingest import IngestEngine

            self.ingest = IngestEngine(
                self, float(ingest_interval),
                max_rows=ingest_max_rows, max_bytes=ingest_max_bytes)
        # Admission control + degradation ladder (server/admission.py):
        # "off" — the default — never constructs a controller, so the
        # query path's only residue is one `is None` check and the
        # legacy path stays byte-identical (escape-hatch convention).
        if admission not in ("off", "on"):
            raise ValueError(
                f"admission must be on|off, got {admission!r}")
        self._admission = None
        if admission == "on":
            from . import admission as admission_mod

            self._admission = admission_mod.AdmissionController(
                capacity_ms_per_s=admission_capacity,
                queue_depth=admission_mod.DEFAULT_QUEUE_DEPTH
                if admission_queue_depth is None else admission_queue_depth,
                queue_timeout=admission_mod.DEFAULT_QUEUE_TIMEOUT
                if admission_queue_timeout is None
                else admission_queue_timeout,
                logger=self.logger)
            if self.ingest is not None:
                # degradation-ladder shed policy for interval merges
                # (overflow-forced merges still run)
                self.ingest.set_shed_probe(self._admission.shed_merges)
        self._resize_writes = []  # queued (kind, kwargs) during RESIZING
        self._resize_writes_lock = threading.Lock()
        self._resize_draining = False  # replay thread active
        # marks the replay thread itself: ITS imports must apply, not
        # re-queue (the queue-while-draining rule is for new client
        # writes, which wait their turn behind the backlog)
        self._resize_replay_tls = threading.local()

    def spmd_step(self, step):
        """Execute one SPMD collective step announced by the coordinator
        (control plane endpoint POST /internal/spmd/step)."""
        if self.spmd is None:
            raise ApiError("spmd mode not enabled on this node")
        return self.spmd.run_step(step)

    def spmd_stream(self, step):
        """Enqueue one STREAMED SPMD step (serve-mode on; POST
        /internal/spmd/stream) — acks before the collective runs."""
        if self.spmd is None:
            raise ApiError("spmd mode not enabled on this node")
        return self.spmd.run_stream(step)

    def batch_executor(self):
        """The executor the coalescer drains into: the local vmapped
        batch pipeline on a single node, the SPMD collective batch
        adapter on a mesh-serving cluster coordinator."""
        if self.cluster is not None and self.spmd is not None:
            from ..cluster.spmd import SpmdBatchRunner

            return SpmdBatchRunner(self)
        return getattr(self.executor, "local", self.executor)

    def spmd_debug(self):
        """GET /debug/spmd payload."""
        if self.spmd is None:
            return {"enabled": False}
        snap = self.spmd.debug_snapshot()
        snap["enabled"] = True
        return snap

    def spmd_debug_steps(self, seq=None, limit=32, local_only=False):
        """GET /debug/spmd/steps[/{seq}] payload: the cross-node step
        timeline (merged + skew-corrected + straggler-attributed), or
        this node's local slice with ?local=true — the same fan-out
        shape as debug_trace, so peers answer without recursing."""
        if self.spmd is None:
            return {"enabled": False}
        if local_only:
            out = self.spmd.steps_local(seq=seq, limit=limit)
        else:
            out = self.spmd.steps_timeline(seq=seq, limit=limit)
        out["enabled"] = True
        return out

    def spmd_set_mode(self, mode):
        """POST /debug/spmd {"serve_mode": ...}: runtime serve-mode
        switch (off|on|shadow|http — http forces the HTTP fan-out for
        same-cluster A/B benching)."""
        if self.spmd is None:
            raise ApiError("spmd mode not enabled on this node")
        return {"serve_mode": self.spmd.set_serve_mode(mode)}

    # -- queries ------------------------------------------------------------

    def _validate_state(self):
        """Most methods are forbidden while RESIZING (reference:
        api.validate api.go:119 + apimethod_string.go)."""
        if self.cluster is not None and self.cluster.state == "RESIZING":
            raise ApiError("cluster is resizing; try again later")

    # Queue cap: past this, imports get the reference's RESIZING rejection
    # instead (backpressure; a resize should finish long before a client
    # can push 10k batches).
    RESIZE_QUEUE_MAX = 10_000
    # Replay attempts per queued write before it is dropped (transient
    # peer errors heal; a write is only lost after all retries, counted
    # in resize_replay_dropped).
    RESIZE_REPLAY_RETRIES = 3

    #: Retry-After on a full resize queue: one drain pass over a full
    #: backlog comfortably finishes within this; a still-running resize
    #: answers the retry with another (cheap) queue append.
    RESIZE_QUEUE_RETRY_AFTER = 5

    def _queue_resize_write(self, kind, kwargs, lsn=None):
        """True = the write was queued for post-resize replay (caller
        returns immediately); False = cluster not resizing, proceed.

        The state re-check happens INSIDE the queue lock, which the drain
        also holds for its swap: either this append lands before a swap
        (drained), or the drain already ran — in which case the state is
        NORMAL here and the write proceeds normally. While a drain is
        replaying, new writes keep queueing behind it so replay order is
        arrival order (a stale queued value must not clobber a newer
        acknowledged one).

        ``lsn``: the write's oplog record (already durable — the append
        happens before the queue check). The drain marks it applied once
        the queued write lands, so a crash mid-drain replays the rest of
        the backlog from the log at next boot instead of dropping it."""
        if self.cluster is None:
            return False
        if getattr(self._resize_replay_tls, "active", False):
            return False  # the drain's own replay: apply directly
        if kwargs.get("remote"):
            # Internal fan-out hop, not a client write: queueing would
            # replay it LOCALLY on a node the resize may have just
            # de-ownered. Reject like the reference; the coordinating
            # node's degraded-write policy reports the failure.
            self._validate_state()
            return False
        with self._resize_writes_lock:
            if self.cluster.state != "RESIZING" \
                    and not self._resize_draining:
                return False
            if len(self._resize_writes) >= self.RESIZE_QUEUE_MAX:
                # 503 + Retry-After, not a generic client error: a full
                # queue is backpressure, and well-behaved clients (our
                # server/client.py included) back off and retry instead
                # of treating it as a server bug. The rejected write's
                # record is marked applied — a 503 promises nothing, and
                # an eternally-unapplied lsn would pin the checkpoint.
                self._oplog_applied(lsn)
                shed_reject(
                    "resize_queue",
                    "cluster is resizing; try again later "
                    "(write queue full)",
                    self.RESIZE_QUEUE_RETRY_AFTER, qclass="batch")
            self._resize_writes.append((kind, kwargs, lsn))
        return True

    def _drain_resize_writes(self):
        """Replay queued imports after a RESIZING->NORMAL transition
        (resize completion OR abort): routing now follows the installed
        topology, so every queued bit lands on its owners. Runs on a
        background thread — the resize manager calls this while holding
        its own lock, and replay fans out over HTTP. Loops until the
        queue is empty so writes arriving mid-drain replay after the
        backlog, preserving arrival order."""
        with self._resize_writes_lock:
            if self._resize_draining or not self._resize_writes:
                return
            self._resize_draining = True

        from ..utils import flightrec
        from ..utils.stats import global_stats

        def replay_one(kind, kwargs, lsn):
            """Apply one queued write with bounded in-place retries.
            Retrying IN PLACE (not re-queueing at the tail) is load-
            bearing: replay order is arrival order, and a failed write
            pushed behind later writes to the same bit could clobber a
            newer acknowledged value. Only after the retries are
            exhausted is the write dropped — that is the documented
            crash-semantics loss, counted in resize_replay_dropped, not
            a silent one.

            Durability: the queued write's oplog record (``lsn``) is
            marked applied only here — on success AND on a counted drop
            (else the checkpoint watermark pins forever on a record no
            one will ever apply). A crash BEFORE this line leaves the
            record below the watermark, so boot replay resumes the
            backlog instead of dropping it."""
            for attempt in range(self.RESIZE_REPLAY_RETRIES):
                try:
                    faultpoints.reached("resize.drain.apply")
                    if kind == "bits":
                        self.import_bits(**kwargs)
                    else:
                        self.import_values(**kwargs)
                    self._oplog_applied(lsn)
                    return
                except Exception:
                    where = {k: kwargs[k] for k in
                             ("index_name", "field_name")}
                    if attempt + 1 < self.RESIZE_REPLAY_RETRIES:
                        global_stats.count("resize_replay_retries")
                        flightrec.record("cluster.replay_retry", kind=kind,
                                         attempt=attempt + 1, **where)
                        self.logger.printf(
                            "resize write replay failed (attempt %d/%d, "
                            "retrying): %s %r", attempt + 1,
                            self.RESIZE_REPLAY_RETRIES, kind, where)
                        time.sleep(0.2 * (2 ** attempt))
                    else:
                        global_stats.count("resize_replay_dropped")
                        flightrec.record("cluster.replay_dropped",
                                         kind=kind, **where)
                        self.logger.printf(
                            "resize write replay DROPPED after %d "
                            "attempts: %s %r", self.RESIZE_REPLAY_RETRIES,
                            kind, where)
                        self._oplog_applied(lsn)  # counted loss, not a wedge

        def replay():
            self._resize_replay_tls.active = True
            while True:
                with self._resize_writes_lock:
                    queued = self._resize_writes
                    self._resize_writes = []
                    if not queued:
                        self._resize_draining = False
                        return
                for kind, kwargs, lsn in queued:
                    replay_one(kind, kwargs, lsn)

        threading.Thread(target=replay, daemon=True,
                         name="resize-write-drain").start()

    # -- durable oplog (storage/oplog.py) ------------------------------------

    def _oplog_append(self, kind, kwargs):
        """Append one import's record BEFORE any queue/apply/ack step;
        returns its LSN (None when no oplog is attached). A boot replay
        re-entering the import path reuses the original record's LSN
        instead of re-appending; the resize drain's own replay likewise
        appends nothing — its queued records already stand in the log."""
        if self.oplog is None:
            return None
        replay_lsn = getattr(self._oplog_tls, "replay_lsn", None)
        if replay_lsn is not None:
            return replay_lsn
        if getattr(self._resize_replay_tls, "active", False):
            return None
        return self.oplog.append(self._oplog_encode(kind, kwargs))

    def _oplog_applied(self, lsn):
        """The write at ``lsn`` finished its synchronous apply (or was
        counted as dropped): advance the applied watermark."""
        if lsn is not None and self.oplog is not None:
            self.oplog.mark_applied(lsn)

    def _oplog_applied_or_defer(self, lsn):
        """Like _oplog_applied, but under fsync=interval with the ingest
        engine active the watermark advance group-commits at the next
        merge instead of per record (bounded by the oplog's gap set; a
        crash before the flush replays the records, which is safe —
        they applied to host fragments idempotently)."""
        ing = self.ingest
        if ing is not None and ing.defer_applied(lsn):
            return
        self._oplog_applied(lsn)

    # -- streaming ingest (exec/ingest.py) ------------------------------------

    def _ingest_admit(self, rows, nbytes):
        """503 + Retry-After back-pressure when the delta buffer is past
        its high-water mark — checked BEFORE the oplog append so a
        rejected import leaves no record behind."""
        ing = self.ingest
        if ing is None:
            return
        retry = ing.admit(rows, nbytes)
        if retry is not None:
            shed_reject(
                "ingest",
                "ingest delta buffer full; merge in progress",
                retry, qclass="batch")

    def _ingest_record(self, index_name, field, shard_rows, nbytes,
                       existence=True):
        """Buffer one applied import's deltas (incl. the index's
        existence field, which add_existence just wrote — roaring
        imports skip it, they never touch existence)."""
        ing = self.ingest
        if ing is None or not shard_rows:
            return
        ing.record(index_name, field, shard_rows, nbytes)
        if not existence:
            return
        idx = self.holder.index(index_name)
        ef = idx.existence_field() if idx is not None else None
        if ef is not None and ef is not field:
            ing.record(index_name, ef, shard_rows, nbytes)

    @staticmethod
    def _ingest_shard_rows(column_ids):
        """{shard: landed rows} for the ingest buffer's accounting."""
        cols = np.asarray(column_ids, dtype=np.uint64)
        if cols.size == 0:
            return {}
        shards, counts = np.unique(cols // np.uint64(SHARD_WIDTH),
                                   return_counts=True)
        return {int(s): int(n) for s, n in zip(shards, counts)}

    def ingest_stats(self):
        """GET /debug/ingest payload ({"enabled": False} when off)."""
        if self.ingest is None:
            return {"enabled": False, "interval_seconds": 0.0}
        return self.ingest.snapshot()

    @staticmethod
    def _oplog_encode(kind, kwargs):
        """JSON-safe record for one import call, captured PRE-translation
        (keys replay through the durable translate stores and get the
        same ids) with datetimes as wire strings and roaring blobs as
        base64. Numeric id/value lists ride as base64 of packed
        fixed-width ints (:func:`_oplog_pack_ints`) — this sits on the
        ack path, and at import batch sizes that serializes ~2x faster
        and smaller than a JSON int list of the same data."""
        rec = {"kind": kind}
        for k, v in kwargs.items():
            if v is None or isinstance(v, (bool, int, float, str)):
                rec[k] = v
            elif k == "timestamps":
                from ..core.timeq import TIME_FORMAT

                rec[k] = [t if (t is None or isinstance(t, str))
                          else t.strftime(TIME_FORMAT) for t in v]
            elif k == "data":
                rec[k] = base64.b64encode(bytes(v)).decode()
            else:
                packed = _oplog_pack_ints(v)
                if packed is None:  # key lists (strings), mixed lists
                    rec[k] = np.asarray(v).tolist()
                else:
                    rec[k] = packed
        return rec

    @staticmethod
    def _oplog_decode_kwargs(record):
        """Invert :meth:`_oplog_encode`'s binary list packing (replay
        path only — cold)."""
        kw = {}
        for k, v in record.items():
            if k == "kind":
                continue
            if isinstance(v, dict) and "__b" in v:
                arr = array.array(_OPLOG_DT[v.get("dt", "i8")])
                arr.frombytes(base64.b64decode(v["__b"]))
                v = arr.tolist()
            kw[k] = v
        return kw

    def _apply_oplog_record(self, record):
        """Replay one decoded record through the NORMAL import path (so
        routing, key translation, existence tracking, and — if the
        cluster is mid-resize at boot — re-queueing all behave exactly
        like the original call did)."""
        kind = record.get("kind")
        kw = self._oplog_decode_kwargs(record)
        if kind == "bits":
            ts = kw.get("timestamps")
            if ts is not None:
                from ..core import timeq

                kw["timestamps"] = [
                    timeq.parse_time(t) if t else None for t in ts]
            return self.import_bits(**kw)
        if kind == "values":
            return self.import_values(**kw)
        if kind == "roaring":
            kw["data"] = base64.b64decode(kw["data"])
            return self.import_roaring(**kw)
        raise ApiError(f"unknown oplog record kind: {kind!r}")

    def replay_oplog(self):
        """Boot-time crash recovery: re-apply every record past the last
        checkpoint, in LSN (== arrival) order. Idempotent — set-bit
        records re-set already-set bits, BSI value records replay
        last-write-wins — so records that were applied (even fsynced)
        before the crash converge to the pre-crash state. Returns the
        number of records applied. Call AFTER the cluster layer is
        attached and BEFORE serving."""
        if self.oplog is None:
            return 0

        def apply(lsn, record):
            self._oplog_tls.replay_lsn = lsn
            try:
                self._apply_oplog_record(record)
            finally:
                self._oplog_tls.replay_lsn = None

        applied, failed = self.holder.replay_oplog(
            self.oplog, apply, logger=self.logger)
        if applied:
            # everything replayed is in fragment WALs now; make it
            # durable and move the checkpoint so the NEXT restart
            # replays only what this boot couldn't finish
            self.holder.sync_fragments()
            self.oplog.checkpoint()
        return applied

    def _oplog_rotate_checkpoint(self, _sealed_last_lsn):
        """Segment rotation is the checkpoint trigger that keeps the log
        bounded: fsync every fragment (making all applied records
        durable BELOW the log) then checkpoint at the applied watermark,
        dropping fully-applied sealed segments. Runs on its own thread —
        the append that tripped the rotation must not wait out a full
        fragment fsync sweep — and the non-blocking lock collapses
        back-to-back rotations into one sweep."""
        if not self._oplog_ckpt_lock.acquire(blocking=False):
            return

        def run():
            try:
                self.holder.sync_fragments()
                self.oplog.checkpoint()
            except Exception as e:  # noqa: BLE001 — retried at next rotate
                self.logger.printf(
                    "oplog checkpoint after rotation failed: %s", e)
            finally:
                self._oplog_ckpt_lock.release()

        threading.Thread(target=run, daemon=True,
                         name="oplog-checkpoint").start()

    def query(self, index_name, pql, shards=None, options=None,
              deadline=None, query_class=None):
        """(reference: api.Query api.go:135)

        `deadline` — absolute time.monotonic() instant parsed from
        `X-Request-Deadline` at the HTTP edge (None = unbounded);
        checked here, at admission queue pop, before each dispatch,
        and forwarded on cluster fan-out. `query_class` — the
        validated `X-Query-Class` header value (None = classify from
        PQL shape)."""
        import contextlib

        from ..utils import flightrec
        from ..utils import profile as profile_mod
        from ..utils import tracing

        self._validate_state()
        if self.holder.index(index_name) is None:
            raise NotFoundError(f"index not found: {index_name}")
        # Expired-on-arrival: drop BEFORE any dispatch can start — the
        # client already gave up, so executing is pure waste (stacked
        # dispatch counters stay flat; tests pin this).
        if deadline is not None and time.monotonic() >= deadline:
            flightrec.record("query.rejected", index=index_name,
                             reason="deadline_expired")
            incident_mod.note_deadline_expiry()
            raise GatewayTimeoutError(
                "request deadline expired before execution")
        # Device-link fail-fast: with the link DOWN a query would wedge
        # behind the dispatch lock until the watchdog fires (75s+ in the
        # r04/r05 postmortems); reject in microseconds instead. DEGRADED
        # still serves — hysteresis keeps one flaky probe from shedding
        # load. Applies to remote fan-out legs too: the coordinator gets
        # a fast 503 it can surface rather than a wedged peer socket.
        from ..utils import devhealth
        if devhealth.is_down():
            retry = devhealth.retry_after_seconds()
            flightrec.record("query.rejected", index=index_name,
                             reason="device_link_down")
            raise ServiceUnavailableError(
                "device link DOWN (canary probes failing); "
                f"retry in {retry:.0f}s", retry_after=retry)
        # Admission gate (server/admission.py): classify, price via the
        # cost model (zero dispatches), debit the class's token bucket —
        # queueing bounded-FIFO in front of the dispatch lock when dry,
        # shedding with 503 + Retry-After past the bound. Remote fan-out
        # legs are NOT re-admitted: the coordinator already paid for the
        # whole query, and double-charging would halve effective
        # capacity (the deadline still rides `options` end-to-end).
        ticket = None
        adm = self._admission
        if adm is not None and not (options is not None
                                    and options.remote):
            ticket = self._admit_query(
                adm, index_name, pql, shards, options, deadline,
                query_class)
        if deadline is not None:
            options = options or ExecOptions()
            options.deadline = deadline
        t_admitted = time.monotonic()
        try:
            return self._query_admitted(
                index_name, pql, shards, options, deadline)
        finally:
            if ticket is not None:
                adm.note_done(ticket, time.monotonic() - t_admitted)

    def _admit_query(self, adm, index_name, pql, shards, options,
                     deadline, query_class):
        """Price + admit one query; translates the controller's
        exceptions onto the unified rejection paths. Parse errors fall
        through un-admitted so the legacy path reports them as the
        usual 400."""
        from ..utils import flightrec
        from . import admission as admission_mod

        try:
            parsed = parse(pql) if isinstance(pql, str) else pql
        except Exception:  # noqa: BLE001 — legacy 400 path owns this
            return None
        qclass = admission_mod.classify(header=query_class, query=parsed)
        is_write = any(c.writes() for c in parsed.calls)
        cost_ms = adm.price(self.executor, self.holder.index(index_name),
                            parsed, shards, options or ExecOptions())
        try:
            return adm.admit(qclass, cost_ms, deadline=deadline,
                             is_write=is_write)
        except admission_mod.Expired as e:
            flightrec.record("query.rejected", index=index_name,
                             reason="deadline_expired_in_queue")
            incident_mod.note_deadline_expiry()
            raise GatewayTimeoutError(str(e)) from e
        except admission_mod.Rejected as e:
            flightrec.record("query.rejected", index=index_name,
                             reason="admission", qclass=e.qclass,
                             state=adm.state)
            shed_reject("admission", str(e), e.retry_after,
                        qclass=e.qclass)

    def _query_admitted(self, index_name, pql, shards, options,
                        deadline=None):
        """The pre-admission body of query() — unchanged legacy path."""
        import contextlib

        from ..utils import flightrec
        from ..utils import profile as profile_mod
        from ..utils import tracing
        # Coalescer routing: batchable single-call reads with default
        # options fuse with concurrent arrivals into one vmapped
        # dispatch. Ineligible queries (and window=0 deployments, where
        # _coalescer is None) continue on the bit-identical legacy path.
        if self._coalescer is not None:
            routed = self._try_coalesce(index_name, pql, shards, options)
            if routed is not None:
                return routed[0]
        # Profile when the request asked (?profile=true) or a slow-query
        # threshold is configured (so a slow query's log line carries the
        # full span tree, not just its total). Remote fan-out legs never
        # profile themselves — the coordinator's profile already captures
        # them as cluster.mapReduce.node spans.
        prof = None
        if not (options is not None and options.remote) and (
                (options is not None and options.profile)
                or (options is not None
                    and getattr(options, "explain", None) == "analyze")
                or self.long_query_time is not None):
            prof = profile_mod.begin(
                index_name, pql if isinstance(pql, str) else str(pql),
                slow_threshold=self.long_query_time)
        t0 = time.monotonic()
        # Watchdog coverage for the WHOLE query: a query wedged below the
        # dispatch lock (or anywhere else) past the deadline trips the
        # stall dump even if no individual dispatch is registered.
        wtoken = flightrec.watch_begin("query", index=index_name)
        try:
            with contextlib.ExitStack() as stack:
                if prof is not None:
                    # adopt the profile's root span so every span below —
                    # and the stacked kernel dispatches — joins its trace
                    stack.enter_context(tracing.with_span(prof.root))
                with tracing.start_span("api.Query", index=index_name):
                    query = parse(pql) if isinstance(pql, str) else pql
                    results = self.executor.execute(
                        index_name, query, shards=shards, options=options)
        except (ApiError,):
            raise
        except Exception as e:
            from ..exec.stacked import DeadlineExceededError
            if isinstance(e, DeadlineExceededError):
                flightrec.record("query.rejected", index=index_name,
                                 reason="deadline_expired_mid_query")
                incident_mod.note_deadline_expiry()
                raise GatewayTimeoutError(str(e)) from e
            raise ApiError(str(e)) from e
        finally:
            flightrec.watch_end(wtoken)
            if prof is not None:
                prof.finish()
        self._log_slow_query(index_name, pql, time.monotonic() - t0, prof)
        # SLO tick: with objectives configured, serving traffic alone
        # keeps burn rates fresh and fires alerts (rate-limited inside;
        # a scrape-free deployment still alerts)
        from ..utils import workload as workload_mod
        workload_mod.maybe_sample_slo()
        if any(c.writes() for c in query.calls):
            self._broadcast_shards_if_changed(index_name)
        return results

    def _try_coalesce(self, index_name, pql, shards, options):
        """Route one query through the coalescer when eligible. Returns
        a 1-tuple (results,) on the coalesced path, or None to fall
        through to the legacy per-query path (ineligible query — or a
        parse error, which the legacy path re-raises with proper ApiError
        wrapping)."""
        from ..utils import flightrec
        from ..utils import tracing
        from ..utils import workload as workload_mod

        if shards is not None or not isinstance(pql, str):
            return None
        o = options
        if o is not None and (o.remote or o.profile or o.explain
                              or o.column_attrs or o.exclude_columns
                              or o.exclude_row_attrs
                              or o.shards is not None
                              or getattr(o, "deadline", None) is not None):
            return None
        try:
            query = parse(pql)
        except Exception:
            return None
        call = query.calls[0] if len(query.calls) == 1 else None
        if call is None or call.writes() \
                or call.name not in self.executor.BATCHABLE_CALLS:
            return None
        t0 = time.monotonic()
        wtoken = flightrec.watch_begin("query", index=index_name)
        try:
            # the span is the HTTP handler's whole wait: queue time +
            # fused execution + demux (coalesce-wait observability)
            with tracing.start_span("coalesce.wait", index=index_name):
                results, bsize, fp = self._coalescer.submit(
                    index_name, query, pql)
        except (ApiError,):
            raise
        except Exception as e:
            raise ApiError(str(e)) from e
        finally:
            flightrec.watch_end(wtoken)
        # end_query ran on the coalescer thread, so THIS thread's
        # last_fingerprint() is stale — pass the member's own through
        self._log_slow_query(index_name, pql, time.monotonic() - t0,
                             batch=bsize, fp=fp)
        workload_mod.maybe_sample_slo()
        return (results,)

    def query_batch(self, index_name, pqls, shards=None):
        """Execute a list of PQL queries as one batched dispatch (the
        explicit POST /index/{i}/query-batch route, sharing the vmapped
        executor path with the coalescer). Returns a list of
        (results, error, batch_size, fingerprint) tuples in request
        order — per-query error isolation, like the coalescer's."""
        self._validate_state()
        if self.holder.index(index_name) is None:
            raise NotFoundError(f"index not found: {index_name}")
        from ..utils import devhealth
        if devhealth.is_down():
            retry = devhealth.retry_after_seconds()
            raise ServiceUnavailableError(
                "device link DOWN (canary probes failing); "
                f"retry in {retry:.0f}s", retry_after=retry)
        if self.cluster is not None:
            # cluster coordinators fan out per query; batching happens
            # on the legs' own dispatch paths
            out = []
            for pql in pqls:
                try:
                    out.append((self.query(index_name, pql,
                                           shards=shards), None, 0, None))
                except Exception as exc:  # noqa: BLE001 — per-query
                    out.append((None, exc, 0, None))
            return out
        return self.executor.execute_batch(
            index_name, list(pqls), shards=shards)

    def batching_stats(self):
        """GET /debug/batching: coalescer occupancy/queue stats plus the
        fused-dispatch counters from the stacked evaluator."""
        if self._coalescer is not None:
            co = self._coalescer.stats()
        else:
            co = {"enabled": False,
                  "window_seconds": self.coalesce_window,
                  "max_queue": self.coalesce_max_queue}
        ex = getattr(self.executor, "local", self.executor)
        st = ex.stacked_stats() if hasattr(ex, "stacked_stats") else {}
        return {
            "coalescer": co,
            "batch_dispatches": st.get("batch_dispatches", 0),
            "batched_queries": st.get("batched_queries", 0),
        }

    def admission_stats(self):
        """GET /debug/admission: the controller's full snapshot —
        ladder state + transition history, per-class token buckets and
        queue occupancy, calibration factor (off → {"enabled": False},
        matching the other gated subsystems' debug payloads)."""
        if self._admission is None:
            return {"enabled": False}
        return self._admission.snapshot()

    def serving_stale(self):
        """True when the degradation ladder is at STALE_OK or worse —
        the HTTP layer marks query responses with "stale": true so
        clients know reads may lag the ingest staleness bound."""
        return self._admission is not None and self._admission.serving_stale()

    def debug_trace(self, trace_id, local_only=False):
        """GET /debug/traces/{trace_id}: one assembled span tree.

        Local spans come from the bounded per-node trace index (plus the
        InMemoryTracer ring when one is installed). On a cluster
        coordinator the default form also pulls every peer's slice of
        the trace (client.debug_trace → the peers' ?local=true form, so
        the fan-out cannot recurse) and merges it with per-node
        clock-skew correction — see utils/tracing.estimate_skew."""
        from ..utils import tracing

        local = tracing.get_trace(trace_id)
        tracer = tracing.get_tracer()
        if hasattr(tracer, "to_dicts"):
            seen = {s["spanID"] for s in local}
            local += [s for s in tracer.to_dicts()
                      if s.get("traceID") == trace_id
                      and s.get("spanID") not in seen]
        if local_only or self.cluster is None \
                or len(self.cluster.nodes) <= 1 \
                or not hasattr(self.executor, "_client"):
            return {"traceID": trace_id, "found": bool(local),
                    "spans": local, "tree": tracing.assemble_tree(local)}
        remote_by_node = {}
        with tracing.with_span(None):  # don't trace the assembly fetches
            for node in list(self.cluster.nodes):
                if node.id == self.cluster.local_id:
                    continue
                try:
                    resp = self.executor._client(node).debug_trace(trace_id)
                except Exception:  # noqa: BLE001 — assembly is best-effort
                    continue
                spans = (resp or {}).get("spans") or []
                if spans:
                    remote_by_node[node.id] = spans
        merged, skew = tracing.merge_remote_spans(local, remote_by_node)
        return {"traceID": trace_id, "found": bool(merged),
                "spans": merged,
                "nodes": {nid: {"spans": len(remote_by_node[nid]),
                                "clock_skew_seconds": round(th, 6)}
                          for nid, th in skew.items()},
                "tree": tracing.assemble_tree(merged)}

    def close(self):
        """Release serving-side background state — the ingest merge
        engine (final flush drains buffered deltas and releases any
        group-committed oplog watermarks) and the query coalescer,
        whose blocked waiters get a 503 instead of hanging on a daemon
        thread that dies with the process. Idempotent; default
        deployments (no engine, no coalescer) no-op."""
        if self._admission is not None:
            self._admission.close()
        if self.ingest is not None:
            self.ingest.close()
        if self._coalescer is not None:
            self._coalescer.close()
        if self.spmd is not None:
            self.spmd.close()

    def _broadcast_shards_if_changed(self, index_name):
        """Push this node's per-index available shards to peers when they
        changed (reference: availableShards gossiped via
        CreateShardMessage / NodeStatus, cluster.go) so shard discovery
        reads the pushed map instead of per-query peer GETs."""
        if self.cluster is None or len(self.cluster.nodes) <= 1:
            return
        idx = self.holder.index(index_name)
        if idx is None:
            return
        shards = set(idx.available_shards())
        if self._pushed_shards.get(index_name) == shards:
            return
        self._pushed_shards[index_name] = shards
        try:
            self._broadcast(MessageType.CREATE_SHARD, {
                "index": index_name,
                "node": self.cluster.local_id,
                "shards": sorted(shards)}, sync=False)
        except Exception:
            # best-effort: the lazy per-peer seed fetch still converges
            pass

    def column_attr_sets(self, index_name, results):
        """Column attr sets for every Row result's columns (reference:
        QueryResponse.ColumnAttrSets populated when the request asks for
        columnAttrs — api.Query/readColumnAttrSets). Only columns that
        actually have attrs appear."""
        from ..core.row import Row

        idx = self.holder.index(index_name)
        if idx is None or idx.column_attr_store is None:
            return []
        cols = set()
        for r in results:
            if isinstance(r, Row):
                cols.update(int(c) for c in r.columns())
        out = []
        for c in sorted(cols):
            attrs = idx.column_attr_store.attrs(c)
            if attrs:
                out.append({"id": c, "attrs": attrs})
        return out

    def _log_slow_query(self, index_name, pql, elapsed, prof=None,
                        batch=None, fp=None):
        """Slow-query log (reference: LongQueryTime api.go:1157). With a
        profile in hand the line carries the full span tree + counters as
        JSON, so the log alone answers dispatch-count vs lock-wait vs
        kernel-time vs fan-out. batch= attributes the fused-dispatch
        occupancy the query rode (1 = solo) so a query slowed by
        coalesce-wait is distinguishable from one slowed by the kernel;
        the coalesced path passes batch/fp explicitly because its
        end_query ran on the coalescer thread, not this one."""
        if (self.long_query_time is not None
                and elapsed > self.long_query_time):
            import json as _json

            from ..utils import flightrec
            from ..utils import workload as workload_mod

            q = pql if isinstance(pql, str) else str(pql)
            # coalesced members pass fp explicitly (executed on the
            # coalescer thread) — this thread's fused stamp is theirs
            # only on the direct path
            coalesced = fp is not None
            # the executor just finished this query on THIS thread, so
            # its fingerprint is in take-last position — slow lines for
            # the same shape grep together across the fleet
            if fp is None:
                fp = workload_mod.last_fingerprint() or "-"
            if batch is None:
                from ..exec.stacked import last_batch_size
                batch = last_batch_size()
            batch = max(1, int(batch))
            # whole-plan fusion stamp (same take-last handoff as the
            # fingerprint): how many top-level calls rode ONE fused
            # device program, 0 = the query ran interpreted. Coalesced
            # members (explicit fp) executed on the coalescer thread,
            # so THIS thread's stamp is stale — they report 0 (the
            # coalescer path never fuses whole plans).
            from ..exec import fusion as fusion_mod
            fused = 0 if coalesced else fusion_mod.last_fused()
            flightrec.record("query.slow", index=index_name,
                             seconds=round(elapsed, 3), pql=q[:200],
                             fingerprint=fp, batch=batch, fused=fused)
            if prof is not None:
                # trace=, fingerprint=, batch=, fused=, and plan= ride
                # ahead of profile=, which stays the LAST field:
                # consumers parse the profile JSON as everything after
                # "profile=" (tests pin this format; they also pin
                # plan= through " plan="/" profile=" splits, so batch=
                # and fused= sit BEFORE plan=). analyze queries stamp a
                # full summary (with ! marking misestimated ops);
                # otherwise derive one from whatever strategy notes the
                # decision points emitted
                plan = prof.tag("plan_summary")
                if not plan:
                    strategies = prof.tag("strategies")
                    plan = ",".join(
                        f"{s.get('op', '?')}={s.get('strategy', '?')}"
                        for s in strategies) if strategies else "-"
                self.logger.printf(
                    "%.03fs SLOW QUERY index=%s %s trace=%s fingerprint=%s "
                    "batch=%d fused=%d plan=%s profile=%s", elapsed,
                    index_name, q[:500], prof.root.trace_id, fp, batch,
                    fused, plan, _json.dumps(prof.to_dict()))
            else:
                self.logger.printf(
                    "%.03fs SLOW QUERY index=%s %s fingerprint=%s "
                    "batch=%d fused=%d",
                    elapsed, index_name, q[:500], fp, batch, fused)

    # -- schema DDL ---------------------------------------------------------

    def create_index(self, name, options=None, remote=False):
        from ..core.holder import HolderError
        from ..core.index import IndexError_

        try:
            idx = self.holder.create_index(
                name, options=options, if_not_exists=remote)
        except HolderError as e:
            raise ConflictError(str(e)) from e
        except IndexError_ as e:
            raise ApiError(str(e)) from e
        if not remote:
            self._broadcast(MessageType.CREATE_INDEX, {
                "index": name,
                "options": idx.options.to_dict()})
        return idx

    def delete_index(self, name, remote=False):
        from ..core.holder import HolderError

        try:
            self.holder.delete_index(name)
        except HolderError as e:
            raise NotFoundError(str(e)) from e
        self._pushed_shards.pop(name, None)
        if self.spmd is not None:
            # mesh-resident stacks of a deleted index must not pin
            # device memory (gen validation already keeps them unread)
            self.spmd.mesh_cache.invalidate_index(name)
        if self.cluster is not None:
            self.cluster.drop_remote_index(name)
        if not remote:
            self._broadcast(MessageType.DELETE_INDEX, {"index": name})

    def create_field(self, index_name, field_name, options=None,
                     remote=False):
        from ..core.index import IndexError_

        idx = self.holder.index(index_name)
        if idx is None:
            raise NotFoundError(f"index not found: {index_name}")
        try:
            field = idx.create_field(
                field_name, options=options, if_not_exists=remote)
        except IndexError_ as e:
            if "already exists" in str(e):
                raise ConflictError(str(e)) from e
            raise ApiError(str(e)) from e
        if not remote:
            self._broadcast(MessageType.CREATE_FIELD, {
                "index": index_name, "field": field_name,
                "options": field.options.to_dict()})
        return field

    def delete_field(self, index_name, field_name, remote=False):
        from ..core.index import IndexError_

        idx = self.holder.index(index_name)
        if idx is None:
            raise NotFoundError(f"index not found: {index_name}")
        try:
            idx.delete_field(field_name)
        except IndexError_ as e:
            raise NotFoundError(str(e)) from e
        if not remote:
            self._broadcast(MessageType.DELETE_FIELD, {
                "index": index_name, "field": field_name})

    def schema(self):
        """Public schema in the reference's camelCase wire shape
        (reference: handleGetSchema + FieldOptions.MarshalJSON)."""
        out = []
        for iname in sorted(self.holder.indexes):
            idx = self.holder.indexes[iname]
            fields = []
            for fname in sorted(idx.public_fields()):
                f = idx.fields[fname]
                fields.append({
                    "name": fname,
                    "options": field_options_to_json(f.options),
                    "shards": f.available_shards(),
                })
            out.append({
                "name": iname,
                "options": {"keys": idx.options.keys,
                            "trackExistence": idx.options.track_existence},
                "fields": fields,
            })
        return {"indexes": out}

    def apply_schema(self, schema):
        """Accepts the camelCase wire shape (reference: handlePostSchema)."""
        for idx_desc in schema.get("indexes", []):
            opts = idx_desc.get("options", {})
            idx = self.holder.create_index(
                idx_desc["name"],
                options=IndexOptions(
                    keys=bool(opts.get("keys", False)),
                    track_existence=bool(opts.get("trackExistence", True))),
                if_not_exists=True)
            for f_desc in idx_desc.get("fields", []):
                idx.create_field(
                    f_desc["name"],
                    options=field_options_from_json(f_desc.get("options")),
                    if_not_exists=True)

    def _broadcast(self, msg_type, payload, sync=True):
        """Schema DDL fans out synchronously to every peer (reference: DDL
        via SendSync broadcast.go / api.go)."""
        if self.cluster is None or len(self.cluster.nodes) <= 1:
            return
        from ..cluster import HTTPBroadcaster

        b = HTTPBroadcaster(self.cluster, self.client_factory)
        if sync:
            b.send_sync(msg_type, payload)
        else:
            b.send_async(msg_type, payload)

    def receive_message(self, data):
        """Handle one control-plane message (reference:
        server.receiveMessage server.go:569)."""
        msg_type, payload = Serializer.unmarshal(data)
        if msg_type == MessageType.CREATE_INDEX:
            self.create_index(
                payload["index"],
                options=IndexOptions.from_dict(payload["options"]),
                remote=True)
        elif msg_type == MessageType.DELETE_INDEX:
            self.delete_index(payload["index"], remote=True)
        elif msg_type == MessageType.CREATE_FIELD:
            self.create_field(
                payload["index"], payload["field"],
                options=FieldOptions.from_dict(payload["options"]),
                remote=True)
        elif msg_type == MessageType.DELETE_FIELD:
            self.delete_field(payload["index"], payload["field"], remote=True)
        elif msg_type == MessageType.RECALCULATE_CACHES:
            self.holder.recalculate_caches()
        elif msg_type == MessageType.CREATE_SHARD:
            # a peer pushed its per-index available shards (gossiped
            # shard map; reference: CreateShardMessage handling)
            if self.cluster is not None \
                    and payload.get("node") != self.cluster.local_id:
                self.cluster.set_remote_shards(
                    payload["node"], payload["index"],
                    payload.get("shards", []))
        elif self.resize is not None and self.resize.receive(
                msg_type, payload):
            pass  # resize/cluster-status/coordinator handled
        elif msg_type == MessageType.NODE_STATE:
            if self.cluster is not None:
                self.cluster.set_node_state(
                    payload["id"], payload["state"])
        elif msg_type in (MessageType.NODE_EVENT, MessageType.NODE_STATUS,
                          MessageType.CLUSTER_STATUS,
                          MessageType.CREATE_VIEW, MessageType.DELETE_VIEW,
                          MessageType.SET_COORDINATOR,
                          MessageType.UPDATE_COORDINATOR,
                          MessageType.RESIZE_INSTRUCTION,
                          MessageType.RESIZE_INSTRUCTION_COMPLETE):
            # single-node mode: no resize manager; tolerated
            pass
        else:
            raise ApiError(f"unhandled message type: {msg_type}")

    # -- imports ------------------------------------------------------------

    def _route_import(self, index_name, shard):
        """(local_apply, remote_nodes) for one shard's import slice
        (reference: api.Import forwards to FragmentNodes, all replicas)."""
        if self.cluster is None or len(self.cluster.nodes) <= 1:
            return True, []
        owners = self.cluster.shard_nodes(index_name, shard)
        local = any(n.id == self.cluster.local_id for n in owners)
        remotes = [n for n in owners if n.id != self.cluster.local_id]
        return local, remotes

    def _fan_out_writes(self, jobs, covered_locally, count_shards=(),
                        index_name=None):
        """Run remote import forwards (one worker per TARGET NODE, its jobs
        sequential — bounded like the executor's per-node mapReduce fan-out)
        and apply the degraded-write policy.

        `jobs`: list of (shard, node, thunk). A forward failure is tolerated
        as long as the shard reached at least one owner (this node or
        another replica) — the lagging replica is repaired by anti-entropy
        (reference: DEGRADED semantics cluster.go:571-583 + fragment
        syncer). A shard that reached NO owner fails the import.

        `count_shards`: shards NOT applied locally; returns their total
        logical change count taken from replica responses (replicas report
        the same count, so max per shard).
        """
        import threading

        from ..cluster.node import NODE_STATE_DOWN

        results, errors, skipped = {}, {}, {}
        lock = threading.Lock()
        by_node = {}
        for shard, node, thunk in jobs:
            by_node.setdefault(node.id, (node, []))[1].append((shard, thunk))

        def run(node, node_jobs):
            for shard, thunk in node_jobs:
                if getattr(node, "state", None) == NODE_STATE_DOWN:
                    # health monitor flagged the node mid-import: don't
                    # burn a full timeout per remaining shard (retried
                    # below only if the shard reaches no other owner)
                    with lock:
                        errors[(shard, node.id)] = ApiError(
                            f"node {node.id} is down")
                        skipped[(shard, node.id)] = thunk
                    continue
                try:
                    resp = thunk()
                    with lock:
                        results[(shard, node.id)] = resp
                except Exception as e:
                    with lock:
                        errors[(shard, node.id)] = e

        threads = [threading.Thread(target=run, args=pair)
                   for pair in by_node.values()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        def uncovered():
            reached = set(covered_locally)
            reached.update(shard for shard, _ in results)
            return sorted({s for (s, _) in errors} - reached)

        # A DOWN mark can be a false positive; when a skipped node was a
        # shard's ONLY owner, attempt the send anyway before failing.
        for (shard, node_id), thunk in skipped.items():
            if shard in uncovered():
                try:
                    results[(shard, node_id)] = thunk()
                    del errors[(shard, node_id)]
                except Exception as e:
                    errors[(shard, node_id)] = e
        failed = uncovered()
        if failed:
            cause = next(e for (s, _), e in errors.items() if s in failed)
            raise ApiError(
                f"import failed: no reachable owner for shards {failed}: "
                f"{cause}")
        for (shard, node_id), e in errors.items():
            self.logger.printf(
                "import: replica %s unreachable for shard %d (%s); "
                "anti-entropy will repair", node_id, shard, e)
        # read-your-writes for shard discovery: this node just confirmed
        # these shards landed on these peers — record them now instead of
        # waiting for the peers' async CREATE_SHARD pushes (which can lag
        # the ack and leave an immediate query missing a fresh shard)
        if self.cluster is not None and index_name is not None:
            for (shard, node_id) in results:
                self.cluster.record_remote_shards(
                    node_id, index_name, [shard])
        remote_changed = {s: 0 for s in count_shards}
        for (shard, _), resp in results.items():
            if shard in remote_changed and isinstance(resp, dict):
                remote_changed[shard] = max(
                    remote_changed[shard], resp.get("changed", 0))
        return results, sum(remote_changed.values())

    def _translate_import_keys(self, index_name, field_name,
                               row_keys, column_keys):
        """String keys -> IDs for bulk imports on the COORDINATING node
        (reference: api.Import key translation api.go:920-1000; remote
        forwards always carry integer IDs). Returns (row_ids, column_ids)
        for whichever key lists were given."""
        idx = self.holder.index(index_name)
        field = idx.field(field_name)
        # validate BOTH options before translating EITHER list: key
        # translation allocates ids permanently (and replicates them), so
        # a rejected import must not leave freshly-minted keys behind
        if column_keys is not None and not idx.options.keys:
            raise ApiError(f"index {index_name} does not use column keys")
        if row_keys is not None and not field.options.keys:
            raise ApiError(f"field {field_name} does not use row keys")
        row_ids = column_ids = None
        # batch API: on a replica (read-only store) per-key translation
        # would cost one primary-forward roundtrip per key
        if column_keys is not None:
            column_ids = list(
                idx.translate_store.translate_keys(column_keys))
        if row_keys is not None:
            row_ids = list(
                field.translate_store.translate_keys(row_keys))
        return row_ids, column_ids

    def import_bits(self, index_name, field_name, row_ids, column_ids,
                    timestamps=None, clear=False, remote=False,
                    row_keys=None, column_keys=None):
        """(reference: api.Import api.go:920 — sort bits by shard, forward
        each slice to all replica owners concurrently; string keys are
        translated here, on the coordinating node)

        During RESIZING the reference rejects imports outright (api.go:101
        methodsResizing admits only fragmentData/abort); we instead QUEUE
        them and replay once the cluster returns to NORMAL — by the
        then-installed topology, so completion AND abort both land every
        bit (policy documented in PARITY.md). The queue is process-memory:
        bounded, and lost on a crash like any unflushed WAL tail.
        Index/field existence is validated BEFORE queueing (DDL is blocked
        while RESIZING, so the check stays valid at replay) — a doomed
        import must 404 now, not vanish into a replay-time log line."""
        field = self._field(index_name, field_name)
        n_points = (len(column_ids) if column_ids is not None
                    else len(column_keys or ()))
        self._ingest_admit(n_points, 16 * n_points)
        kwargs = dict(index_name=index_name, field_name=field_name,
                      row_ids=row_ids, column_ids=column_ids,
                      timestamps=timestamps, clear=clear,
                      remote=remote, row_keys=row_keys,
                      column_keys=column_keys)
        lsn = self._oplog_append("bits", kwargs)
        faultpoints.reached("import.post-append")
        if self._queue_resize_write("bits", kwargs, lsn=lsn):
            return 0
        try:
            if row_keys is not None or column_keys is not None:
                t_rows, t_cols = self._translate_import_keys(
                    index_name, field_name, row_keys, column_keys)
                if t_rows is not None:
                    row_ids = t_rows
                if t_cols is not None:
                    column_ids = t_cols
            if remote or self.cluster is None or len(self.cluster.nodes) <= 1:
                changed = field.import_bits(
                    row_ids, column_ids, timestamps=timestamps, clear=clear)
                self.holder.index(index_name).add_existence(column_ids)
                if self.ingest is not None:
                    self._ingest_record(
                        index_name, field,
                        self._ingest_shard_rows(column_ids),
                        16 * len(column_ids))
                self._broadcast_shards_if_changed(index_name)
                faultpoints.reached("import.pre-ack")
                return changed

            import numpy as np

            from ..core.timeq import TIME_FORMAT

            row_ids = np.asarray(row_ids, dtype=np.uint64)
            column_ids = np.asarray(column_ids, dtype=np.uint64)
            shards = column_ids // np.uint64(SHARD_WIDTH)
            changed = 0
            jobs, covered, remote_only = [], set(), set()
            for shard in np.unique(shards):
                shard = int(shard)
                mask = shards == shard
                local, remotes = self._route_import(index_name, shard)
                slice_rows = row_ids[mask]
                slice_cols = column_ids[mask]
                slice_ts = None
                if timestamps is not None:
                    ts_arr = np.asarray(timestamps, dtype=object)
                    slice_ts = ts_arr[mask].tolist()
                if local:
                    changed += field.import_bits(
                        slice_rows, slice_cols, timestamps=slice_ts,
                        clear=clear)
                    self.holder.index(index_name).add_existence(slice_cols)
                    covered.add(shard)
                else:
                    remote_only.add(shard)
                wire_ts = None
                if slice_ts is not None:
                    wire_ts = [
                        t.strftime(TIME_FORMAT) if t is not None else None
                        for t in slice_ts]
                for node in remotes:
                    jobs.append((shard, node, (
                        lambda n=node, r=slice_rows, c=slice_cols, w=wire_ts:
                        self.client_factory(n.uri).import_bits(
                            index_name, field_name, r.tolist(), c.tolist(),
                            timestamps=w, clear=clear, remote=True))))
            if self.ingest is not None and covered:
                self._ingest_record(
                    index_name, field,
                    {s: int((shards == np.uint64(s)).sum())
                     for s in covered},
                    16 * len(column_ids))
            _, remote_changed = self._fan_out_writes(
                jobs, covered, count_shards=remote_only,
                index_name=index_name)
            self._broadcast_shards_if_changed(index_name)
            faultpoints.reached("import.pre-ack")
            return changed + remote_changed
        finally:
            # an exception here means NO ack went out, so the record
            # needs no replay guarantee — mark it applied either way so
            # one failed import can't pin the checkpoint watermark
            # forever (a process crash skips this; that's the point)
            self._oplog_applied_or_defer(lsn)

    def import_values(self, index_name, field_name, column_ids, values,
                      remote=False, column_keys=None, clear=False):
        """clear=True removes the listed columns' values (reference:
        ImportValue with OptImportOptionsClear api.go:1035 ->
        field.importValue field.go:1285)."""
        field = self._field(index_name, field_name)
        n_points = (len(column_ids) if column_ids is not None
                    else len(column_keys or ()))
        self._ingest_admit(n_points, 16 * n_points)
        kwargs = dict(index_name=index_name, field_name=field_name,
                      column_ids=column_ids, values=values,
                      remote=remote, column_keys=column_keys,
                      clear=clear)
        lsn = self._oplog_append("values", kwargs)
        faultpoints.reached("import.post-append")
        if self._queue_resize_write("values", kwargs, lsn=lsn):
            return 0
        try:
            if column_keys is not None:
                _, column_ids = self._translate_import_keys(
                    index_name, field_name, None, column_keys)
            if remote or self.cluster is None or len(self.cluster.nodes) <= 1:
                changed = field.import_values(column_ids, values, clear=clear)
                if not clear:
                    self.holder.index(index_name).add_existence(column_ids)
                if self.ingest is not None:
                    self._ingest_record(
                        index_name, field,
                        self._ingest_shard_rows(column_ids),
                        16 * len(column_ids), existence=not clear)
                self._broadcast_shards_if_changed(index_name)
                faultpoints.reached("import.pre-ack")
                return changed

            import numpy as np

            column_ids = np.asarray(column_ids, dtype=np.uint64)
            values = np.asarray(values, dtype=np.int64)
            shards = column_ids // np.uint64(SHARD_WIDTH)
            changed = 0
            jobs, covered, remote_only = [], set(), set()
            for shard in np.unique(shards):
                shard = int(shard)
                mask = shards == shard
                local, remotes = self._route_import(index_name, shard)
                if local:
                    changed += field.import_values(
                        column_ids[mask], values[mask], clear=clear)
                    if not clear:
                        self.holder.index(index_name).add_existence(
                            column_ids[mask])
                    covered.add(shard)
                else:
                    remote_only.add(shard)
                for node in remotes:
                    jobs.append((shard, node, (
                        lambda n=node, c=column_ids[mask], v=values[mask]:
                        self.client_factory(n.uri).import_values(
                            index_name, field_name, c.tolist(), v.tolist(),
                            remote=True, clear=clear))))
            if self.ingest is not None and covered:
                self._ingest_record(
                    index_name, field,
                    {s: int((shards == np.uint64(s)).sum())
                     for s in covered},
                    16 * len(column_ids), existence=not clear)
            _, remote_changed = self._fan_out_writes(
                jobs, covered, count_shards=remote_only,
                index_name=index_name)
            self._broadcast_shards_if_changed(index_name)
            faultpoints.reached("import.pre-ack")
            return changed + remote_changed
        finally:
            self._oplog_applied_or_defer(lsn)

    def import_roaring(self, index_name, field_name, shard, data,
                       clear=False, view="standard", remote=False):
        """(reference: api.ImportRoaring api.go:368 — fastest ingest; like
        bit imports, the blob routes to every replica owner of the shard)"""
        self._validate_state()
        field = self._field(index_name, field_name)
        shard = int(shard)
        self._ingest_admit(1, len(data))
        lsn = self._oplog_append("roaring", dict(
            index_name=index_name, field_name=field_name, shard=shard,
            data=data, clear=clear, view=view, remote=remote))
        faultpoints.reached("import.post-append")
        try:
            local, remotes = (True, []) if remote else \
                self._route_import(index_name, shard)
            changed = 0
            if local:
                v = field.create_view_if_not_exists(view)
                frag = v.create_fragment_if_not_exists(shard)
                changed = frag.import_roaring(data, clear=clear)
                if self.ingest is not None:
                    self._ingest_record(
                        index_name, field, {shard: 1}, len(data),
                        existence=False)
            jobs = [(shard, node, (
                lambda n=node: self.client_factory(n.uri).import_roaring(
                    index_name, field_name, shard, data, clear=clear,
                    view=view, remote=True))) for node in remotes]
            _, remote_changed = self._fan_out_writes(
                jobs, {shard} if local else set(),
                count_shards=() if local else {shard},
                index_name=index_name)
            self._broadcast_shards_if_changed(index_name)
            faultpoints.reached("import.pre-ack")
            return changed if local else remote_changed
        finally:
            self._oplog_applied_or_defer(lsn)

    def _field(self, index_name, field_name):
        idx = self.holder.index(index_name)
        if idx is None:
            raise NotFoundError(f"index not found: {index_name}")
        field = idx.field(field_name)
        if field is None:
            raise NotFoundError(f"field not found: {field_name}")
        return field

    # -- export -------------------------------------------------------------

    def export_csv(self, index_name, field_name, shard):
        """(reference: api.ExportCSV api.go:500) row,col lines for one
        shard, translating ids back to keys on keyed fields/indexes
        (api.go:538-557) so an export re-imports losslessly."""
        idx = self.holder.index(index_name)
        field = self._field(index_name, field_name)
        view = field.view()
        frag = view.fragment(int(shard)) if view else None
        buf = io.StringIO()
        writer = csv.writer(buf)
        if frag is None:
            return buf.getvalue()

        def _batch_translate(store, ids, what):
            """Batched id->key with loud failure: a silently empty CSV
            cell would break the lossless export->import round trip
            (e.g. a replica whose translate sync hasn't caught up)."""
            out = {}
            for id_, key in zip(ids, store.translate_ids(ids)):
                if key is None:
                    raise ApiError(
                        f"translating {what} id {id_} failed: key not "
                        "found (translate replication may be catching "
                        "up; retry or export from the primary)")
                out[id_] = key
            return out

        row_ids = frag.row_ids()
        row_out = {r: r for r in row_ids}
        if field.options.keys:
            row_out = _batch_translate(
                field.translate_store, row_ids, "row")
        col_memo = {}
        for row_id in row_ids:
            cols = [int(c) for c in frag.row_columns(row_id)]
            if idx.options.keys:
                missing = [c for c in cols if c not in col_memo]
                if missing:
                    col_memo.update(_batch_translate(
                        idx.translate_store, missing, "column"))
                for col in cols:
                    writer.writerow([row_out[row_id], col_memo[col]])
            else:
                for col in cols:
                    writer.writerow([row_out[row_id], col])
        return buf.getvalue()

    # -- info/status --------------------------------------------------------

    def info(self):
        return {"shardWidth": SHARD_WIDTH, "version": __version__}

    def status(self, include_remote_observability=False):
        state = "NORMAL"
        replica_n = 1
        nodes = []
        if self.cluster is not None:
            state = self.cluster.state
            replica_n = self.cluster.replica_n
            nodes = self.cluster.nodes_json()
        else:
            nodes = [{"id": "local", "uri": {"scheme": "http"},
                      "isCoordinator": True, "state": "READY"}]
        # replicaN lets a --join'ing node inherit the replication factor
        out = {"state": state, "nodes": nodes, "replicaN": replica_n,
               "localShardWidth": SHARD_WIDTH}
        # Per-node HBM/kernel summaries. The local node's summary is
        # computed in-process (always cheap); peer summaries ride the
        # debug endpoints via server/client.py, coordinator-only and
        # opt-in (?observability=true) so readiness polls never block on
        # a partitioned peer.
        obs = {}
        local_summary = self._node_observability()
        if local_summary is not None:
            local_id = self.cluster.local_id if self.cluster is not None \
                else "local"
            obs[local_id] = local_summary
        if include_remote_observability and self.cluster is not None:
            coord = self.cluster.coordinator
            if coord is not None and coord.id == self.cluster.local_id:
                for node in self.cluster.nodes:
                    if node.id == self.cluster.local_id:
                        continue
                    obs[node.id] = self._peer_observability(node)
        if obs:
            out["observability"] = obs
        return out

    def _node_observability(self):
        """Compact local HBM + kernel + device-link summary for /status
        (totals only — the full rankings live at /debug/hbm,
        /debug/kernels, and /debug/device)."""
        from ..exec import plan as plan_mod
        from ..utils import devhealth
        from ..utils import workload as workload_mod

        local = getattr(self.executor, "local", self.executor)
        if not hasattr(local, "hbm_stats"):
            return None
        hbm = local.hbm_stats(top=0)
        kernels = local.kernel_stats(include_costs=False)["kernels"]
        out = {
            "hbm": {k: hbm[k] for k in (
                "total_bytes", "stack_bytes", "stack_entries",
                "rows_stack_bytes", "rows_stack_entries")},
            "kernels": {
                kind: {"count": v["count"],
                       "seconds": round(v["seconds"], 6)}
                for kind, v in sorted(kernels.items())},
            "plans": plan_mod.stats(),
            "device_link": devhealth.summary(),
            # workload observatory roll-up: what runs, what's hot, and
            # whether serving is inside its objectives (full rankings
            # live at /debug/workload, /debug/heat, /debug/slo)
            "workload": workload_mod.table().summary(),
            "heat": workload_mod.heat().summary(),
            "slo": workload_mod.slo().summary(),
        }
        if self._admission is not None:
            out["admission"] = self._admission.summary()
        if self.oplog is not None:
            out["oplog"] = self.oplog.summary(compact=True)
        if self.spmd is not None:
            # the primary data plane's roll-up: serve mode, step
            # lifecycle, stream health, mesh-cache stats (full views at
            # /debug/spmd and /debug/spmd/steps)
            out["spmd"] = self.spmd.summary()
        return out

    #: peer observability fetches must never wedge a /status response
    #: behind a dead node (client default is 30s)
    OBSERVABILITY_PEER_TIMEOUT = 2

    def _peer_observability(self, node):
        """One peer's compact summary via its debug endpoints; failures
        degrade to an error entry instead of failing /status."""
        try:
            client = self.client_factory(node.uri)
            if hasattr(client, "timeout"):
                client.timeout = self.OBSERVABILITY_PEER_TIMEOUT
            hbm = client.debug_hbm(top=0)
            kernels = client.debug_kernels(costs=False).get("kernels", {})
            out = {
                "hbm": {k: hbm.get(k) for k in (
                    "total_bytes", "stack_bytes", "stack_entries",
                    "rows_stack_bytes", "rows_stack_entries")},
                "kernels": {
                    kind: {"count": v.get("count"),
                           "seconds": round(v.get("seconds", 0.0), 6)}
                    for kind, v in sorted(kernels.items())},
            }
            plans = client.debug_plans(limit=0)
            out["plans"] = {k: plans.get(k) for k in
                            ("retained", "misestimates_flagged")}
            # device-link roll-up: the coordinator's /status answers
            # "which node's tunnel is dead" without a per-node ssh
            dev = client.debug_device(limit=0)
            out["device_link"] = {k: dev.get(k) for k in
                                  ("state", "state_since",
                                   "consecutive_failures", "probes",
                                   "last")}
            op = client.debug_oplog()
            if op.get("enabled"):
                out["oplog"] = {k: op.get(k) for k in
                                ("fsync", "last_lsn", "checkpoint_lsn",
                                 "replay_lag", "unapplied", "segments",
                                 "truncated_tails")}
            # workload observatory roll-up (top=0/1: counters, not
            # rankings — the full views stay on each node's debug
            # endpoints)
            wl = client.debug_workload(top=1)
            out["workload"] = {k: wl.get(k) for k in
                               ("total_queries", "unique_fingerprints",
                                "evicted")}
            top_freq = wl.get("by_frequency") or []
            out["workload"]["top"] = {
                k: top_freq[0].get(k)
                for k in ("fingerprint", "shape", "count")} \
                if top_freq else None
            ht = client.debug_heat(top=0)
            out["heat"] = {
                "tracked": ht.get("tracked"),
                "hot_but_not_resident":
                    ht.get("hot_but_not_resident_total"),
                "resident_but_cold":
                    ht.get("resident_but_cold_total")}
            sl = client.debug_slo()
            out["slo"] = {
                "objectives": len(sl.get("objectives") or []),
                "alerting": [o.get("name")
                             for o in sl.get("objectives") or []
                             if o.get("alerting")],
                "alerts_total": sl.get("alerts_total")}
            adm = client.debug_admission()
            if adm.get("enabled"):
                out["admission"] = {k: adm.get(k) for k in
                                    ("state", "state_age_seconds",
                                     "calibration")}
            sp = client.debug_spmd()
            if sp.get("enabled"):
                out["spmd"] = {
                    "serve_mode": sp.get("serve_mode"),
                    "steps": sp.get("steps"),
                    "stream": sp.get("stream"),
                    "mesh_cache": {
                        k: (sp.get("mesh_cache") or {}).get(k)
                        for k in ("hits", "misses", "entries",
                                  "bytes")},
                }
            return out
        except Exception as e:  # noqa: BLE001 — degraded, not fatal
            return {"error": str(e)}

    def shards_max(self):
        out = {}
        for name, idx in self.holder.indexes.items():
            shards = idx.available_shards()
            out[name] = shards[-1] if shards else 0
        return {"standard": out}

    def recalculate_caches(self):
        """(reference: api.RecalculateCaches api.go)"""
        self.holder.recalculate_caches()
        self._broadcast(MessageType.RECALCULATE_CACHES, {}, sync=False)
        return None

    # -- node-to-node internals ---------------------------------------------

    def index_shards(self, index_name):
        idx = self.holder.index(index_name)
        if idx is None:
            raise NotFoundError(f"index not found: {index_name}")
        return {"shards": idx.available_shards()}

    def shard_nodes(self, index_name, shard):
        """Owner nodes of one shard, as node JSON (reference:
        api.ShardNodes api.go:1086, served by handler.go:311)."""
        if self.cluster is None:
            return [{"id": "local", "isCoordinator": True}]
        return [n.to_json()
                for n in self.cluster.shard_nodes(index_name, int(shard))]

    def delete_available_shard(self, index_name, field_name, shard):
        """Forget a remotely-advertised shard for a field (reference:
        api.DeleteAvailableShard api.go:1266 -> Field.RemoveAvailableShard
        field.go:513; used when a remote's shard advertisement turns out
        stale).

        DIVERGENCE from the reference: the reference tracks availability
        per-FIELD (each field carries its own availableShards bitmap);
        here availability is tracked per-INDEX in the gossiped shard map
        (queries fan out by index, and a shard with any data in any
        field has index data). So although this route accepts — and
        validates — a field name for wire compatibility, removal drops
        the shard from every peer's record for the WHOLE index, not just
        the named field. Callers deleting a stale advertisement for one
        field of a multi-field index remove it for the others too; the
        next gossip push from the owning node restores it if any field
        still has data. See docs/architecture.md ("Cluster")."""
        self._field(index_name, field_name)  # 404 on unknown index/field
        if self.cluster is not None:
            self.cluster.remove_remote_shard(index_name, int(shard))
        return None

    def _fragment(self, index_name, field_name, view_name, shard):
        field = self._field(index_name, field_name)
        view = field.view(view_name)
        frag = view.fragment(int(shard)) if view else None
        if frag is None:
            raise NotFoundError(
                f"fragment not found: {index_name}/{field_name}/"
                f"{view_name}/{shard}")
        return frag

    def shard_fragments(self, index_name, shard):
        """Every (field, view) fragment present for a shard on this node
        (resize streaming discovery; the destination can't know which
        views exist — they're data-dependent)."""
        idx = self.holder.index(index_name)
        if idx is None:
            raise NotFoundError(f"index not found: {index_name}")
        shard = int(shard)
        out = []
        for field in idx.fields.values():
            for vname, view in field.views.items():
                if view.fragment(shard) is not None:
                    out.append({"field": field.name, "view": vname})
        return {"fragments": out}

    def fragment_blocks(self, index_name, field_name, view_name, shard):
        """(reference: /internal/fragment/blocks handler.go:300)"""
        frag = self._fragment(index_name, field_name, view_name, shard)
        return {"blocks": [{"id": bid, "checksum": chk.hex()}
                           for bid, chk in frag.blocks()]}

    def fragment_block_data(self, index_name, field_name, view_name, shard,
                            block):
        frag = self._fragment(index_name, field_name, view_name, shard)
        rows, cols = frag.block_data(int(block))
        return {"rowIDs": [int(r) for r in rows],
                "columnIDs": [int(c) for c in cols]}

    def fragment_data(self, index_name, field_name, view_name, shard):
        """Whole fragment as a serialized roaring blob (reference:
        /internal/fragment/data — resize streaming)."""
        from ..roaring import serialize

        frag = self._fragment(index_name, field_name, view_name, shard)
        return serialize(frag.storage)

    def translate_data(self, index_name, field_name="", offset=0):
        """Translate-entry feed from a given ID offset (reference:
        http/translator.go + holder.go:702-880)."""
        idx = self.holder.index(index_name)
        if idx is None:
            raise NotFoundError(f"index not found: {index_name}")
        if field_name:
            field = idx.field(field_name)
            if field is None:
                raise NotFoundError(f"field not found: {field_name}")
            store = field.translate_store
        else:
            store = idx.translate_store
        if store is None:
            return {"entries": []}
        return {"entries": [e.to_json() for e in store.entries(int(offset))]}

    def translate_keys_create(self, index_name, field_name, keys):
        """Allocate ids for keys — served by the chain head; a replica
        receiving this forwards through its own remote_create hook
        (reference: translate key writes route to the primary,
        http/handler.go:518-522)."""
        idx = self.holder.index(index_name)
        if idx is None:
            raise NotFoundError(f"index not found: {index_name}")
        if field_name:
            field = idx.field(field_name)
            if field is None:
                raise NotFoundError(f"field not found: {field_name}")
            store = field.translate_store
        else:
            store = idx.translate_store
        if store is None:
            raise ApiError(
                f"keys not enabled: {index_name}/{field_name or '<index>'}")
        return {"ids": store.translate_keys(list(keys), create=True)}

    def _attr_store(self, index_name, field_name=""):
        idx = self.holder.index(index_name)
        if idx is None:
            raise NotFoundError(f"index not found: {index_name}")
        if field_name:
            field = idx.field(field_name)
            if field is None:
                raise NotFoundError(f"field not found: {field_name}")
            return field.row_attr_store
        return idx.column_attr_store

    def attr_blocks(self, index_name, field_name=""):
        """(reference: attr diff api.go:817-891)"""
        store = self._attr_store(index_name, field_name)
        if store is None:
            return {"blocks": []}
        return {"blocks": [{"id": bid, "checksum": chk}
                           for bid, chk in store.blocks()]}

    def attr_block_data(self, index_name, field_name="", block=0):
        store = self._attr_store(index_name, field_name)
        if store is None:
            return {"attrs": {}}
        return {"attrs": {str(id): attrs for id, attrs
                          in store.block_data(int(block)).items()}}

    def attr_diff(self, index_name, field_name, remote_blocks):
        """Attrs from every local block that differs from (or is absent
        in) the caller's checksum list — one round trip of the attr
        anti-entropy protocol (reference: api.IndexAttrDiff api.go:817 +
        attrBlocks.Diff attr.go:90; served at
        /internal/index/{i}/attr/diff and .../field/{f}/attr/diff, which
        a stock internal client posts to)."""
        store = self._attr_store(index_name, field_name)  # 404s for us
        if store is None:
            return {"attrs": {}}
        return {"attrs": {str(id): a
                          for id, a in store.diff(remote_blocks).items()}}

    def hosts(self):
        if self.cluster is not None:
            return self.cluster.nodes_json()
        return [{"id": "local", "isCoordinator": True}]

    # -- resize admin (reference: api.go:1193-1267) ---------------------------

    def _resize_manager(self):
        from ..cluster import ResizeError

        if self.resize is None:
            raise ApiError("not a cluster")
        if not self.cluster.is_coordinator():
            coord = self.cluster.coordinator
            raise ApiError(
                f"not the coordinator (coordinator: "
                f"{coord.id if coord else 'unknown'})")
        return self.resize, ResizeError

    def resize_add_node(self, node_json):
        from ..cluster import Node

        mgr, ResizeError = self._resize_manager()
        node = Node.from_json(node_json)
        try:
            return mgr.add_node(node).to_json()
        except ResizeError as e:
            raise ApiError(str(e)) from e

    def resize_remove_node(self, node_id):
        mgr, ResizeError = self._resize_manager()
        try:
            return mgr.remove_node(node_id).to_json()
        except ResizeError as e:
            raise ApiError(str(e)) from e

    def resize_abort(self):
        mgr, ResizeError = self._resize_manager()
        try:
            return mgr.abort().to_json()
        except ResizeError as e:
            raise ApiError(str(e)) from e

    def resize_status(self):
        if self.resize is None or self.resize.job is None:
            return {"job": None}
        return {"job": self.resize.job.to_json()}

    def set_coordinator(self, node_id):
        """(reference: api.SetCoordinator api.go:1221)"""
        if self.cluster is None:
            raise ApiError("not a cluster")
        if self.cluster.node(node_id) is None:
            raise ApiError(f"node not in cluster: {node_id}")
        for n in self.cluster.nodes:
            n.is_coordinator = (n.id == node_id)
        self.cluster.save_topology()
        self._broadcast(MessageType.SET_COORDINATOR, {"id": node_id})
        return {"coordinator": node_id}
