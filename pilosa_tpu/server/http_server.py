"""HTTP transport (reference: http/handler.go).

Stdlib ThreadingHTTPServer + a regex router mirroring the reference's REST
surface (route table: http/handler.go:273-322). JSON in/out using the
reference's wire shapes; roaring imports are raw binary bodies.
"""

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..core.index import IndexOptions
from ..core import timeq
from .api import ApiError, GatewayTimeoutError, NotFoundError, \
    ServiceUnavailableError, field_options_from_json, \
    field_options_to_json, result_to_json


class Route:
    def __init__(self, method, pattern, fn, args=None):
        self.method = method
        # the raw pattern doubles as the route's metrics label: bounded
        # cardinality, unlike raw request paths (satellite: per-route tags)
        self.pattern = pattern
        self.regex = re.compile("^" + pattern + "$")
        self.fn = fn
        # allowed query-string arg names; None = no validation
        # (reference: queryArgValidator middleware http/handler.go:320 +
        # the per-route queryValidationSpec table :174-200 — unknown args
        # 400 instead of being silently ignored)
        self.args = frozenset(args) if args is not None else None


class PilosaHTTPServer:
    """Owns the listening socket and the route table."""

    def __init__(self, api, host="127.0.0.1", port=10101, stats=None,
                 tls_cert=None, tls_key=None, allowed_origins=None):
        from ..utils.stats import global_stats

        self.api = api
        self.host = host
        self.port = port
        # The configured metrics sink (reference: server.go:419); the
        # global registry stays the default so /metrics always has data.
        self.stats = stats if stats is not None else global_stats
        # TLS (reference: server/tlsconfig.go; config tls.certificate/key)
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        # CORS (reference: http/handler.go:83-91 OptHandlerAllowedOrigins):
        # origins allowed to hit the API from a browser; "*" allows all.
        self.allowed_origins = list(allowed_origins or [])
        self.routes = self._build_routes()
        self._httpd = None
        self._thread = None
        self._tls_ctx = None

    # -- route table (reference: http/handler.go:273-322) --------------------

    def _build_routes(self):
        a = self.api
        return [
            Route("GET", r"/", self._home),
            Route("GET", r"/index", self._get_indexes),
            Route("POST", r"/index/(?P<index>[^/]+)", self._post_index),
            Route("GET", r"/index/(?P<index>[^/]+)", self._get_index),
            Route("DELETE", r"/index/(?P<index>[^/]+)", self._delete_index),
            Route("POST", r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)",
                  self._post_field),
            Route("DELETE", r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)",
                  self._delete_field),
            Route("POST", r"/index/(?P<index>[^/]+)/query",
                  self._post_query,
                  args=("shards", "remote", "columnAttrs",
                        "excludeRowAttrs", "excludeColumns", "profile",
                        "explain")),
            Route("POST", r"/index/(?P<index>[^/]+)/query-batch",
                  self._post_query_batch, args=("shards",)),
            Route("POST",
                  r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/import",
                  self._post_import,
                  args=("clear", "remote", "ignoreKeyCheck")),
            Route("POST",
                  r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)"
                  r"/import-roaring/(?P<shard>[0-9]+)",
                  self._post_import_roaring,
                  args=("view", "clear", "remote")),
            Route("GET", r"/export", self._get_export,
                  args=("index", "field", "shard")),
            Route("GET", r"/schema", self._get_schema),
            Route("POST", r"/schema", self._post_schema),
            Route("GET", r"/status", self._get_status),
            Route("GET", r"/healthz", self._get_healthz),
            Route("GET", r"/readyz", self._get_readyz),
            Route("GET", r"/info", self._get_info),
            Route("GET", r"/version", self._get_version),
            Route("GET", r"/internal/shards/max", self._get_shards_max),
            Route("GET", r"/internal/nodes", self._get_nodes),
            Route("GET", r"/internal/index/(?P<index>[^/]+)/shards",
                  self._get_index_shards),
            Route("GET",
                  r"/internal/index/(?P<index>[^/]+)/shard/(?P<shard>[0-9]+)"
                  r"/fragments",
                  self._get_shard_fragments),
            Route("POST", r"/internal/cluster/message", self._post_message),
            Route("POST", r"/internal/spmd/step", self._post_spmd_step),
            Route("POST", r"/internal/spmd/stream",
                  self._post_spmd_stream),
            Route("POST", r"/internal/spmd/validate",
                  self._post_spmd_validate),
            Route("POST", r"/internal/spmd/initiate",
                  self._post_spmd_initiate),
            Route("GET", r"/internal/spmd/stats", self._get_spmd_stats),
            Route("GET", r"/internal/fragment/nodes",
                  self._get_fragment_nodes),
            Route("DELETE",
                  r"/internal/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)"
                  r"/remote-available-shards/(?P<shard>[0-9]+)",
                  self._delete_remote_available_shard),
            Route("GET", r"/internal/fragment/blocks",
                  self._get_fragment_blocks,
                  args=("index", "field", "view", "shard")),
            Route("GET", r"/internal/fragment/block/data",
                  self._get_fragment_block_data),
            Route("GET", r"/internal/fragment/data",
                  self._get_fragment_data,
                  args=("index", "field", "view", "shard")),
            Route("GET", r"/internal/translate/data",
                  self._get_translate_data),
            Route("POST", r"/internal/translate/data",
                  self._post_translate_data),
            Route("POST", r"/internal/translate/keys",
                  self._post_translate_keys),
            Route("GET", r"/internal/attr/blocks", self._get_attr_blocks),
            Route("GET", r"/internal/attr/data", self._get_attr_block_data),
            Route("POST", r"/internal/index/(?P<index>[^/]+)/attr/diff",
                  self._post_index_attr_diff),
            Route("POST",
                  r"/internal/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)"
                  r"/attr/diff",
                  self._post_field_attr_diff),
            Route("POST", r"/recalculate-caches", self._recalculate_caches),
            Route("POST", r"/cluster/resize/add-node", self._resize_add_node),
            Route("POST", r"/cluster/resize/remove-node",
                  self._resize_remove_node),
            Route("POST", r"/cluster/resize/abort", self._resize_abort),
            Route("GET", r"/cluster/resize/status", self._resize_status),
            Route("POST", r"/cluster/resize/set-coordinator",
                  self._set_coordinator),
            Route("GET", r"/metrics", self._get_metrics),
            Route("GET", r"/debug", self._get_debug_index),
            Route("GET", r"/debug/vars", self._get_debug_vars),
            Route("GET", r"/debug/queries", self._get_debug_queries),
            Route("GET", r"/debug/plans", self._get_debug_plans,
                  args=("limit",)),
            Route("GET", r"/debug/traces", self._get_debug_traces),
            Route("GET", r"/debug/traces/(?P<trace_id>[^/?]+)",
                  self._get_debug_trace, args=("local",)),
            Route("GET", r"/debug/flightrecorder",
                  self._get_flightrecorder, args=("limit",)),
            Route("GET", r"/debug/hbm", self._get_debug_hbm,
                  args=("top",)),
            Route("GET", r"/debug/kernels", self._get_debug_kernels,
                  args=("costs",)),
            Route("GET", r"/debug/device", self._get_debug_device,
                  args=("limit",)),
            Route("GET", r"/debug/dispatch", self._get_debug_dispatch),
            Route("GET", r"/debug/batching", self._get_debug_batching),
            Route("GET", r"/debug/workload", self._get_debug_workload,
                  args=("top",)),
            Route("GET", r"/debug/heat", self._get_debug_heat,
                  args=("top",)),
            Route("GET", r"/debug/optimizer", self._get_debug_optimizer),
            Route("GET", r"/debug/fusion", self._get_debug_fusion),
            Route("GET", r"/debug/spmd", self._get_debug_spmd),
            Route("POST", r"/debug/spmd", self._post_debug_spmd),
            Route("GET", r"/debug/spmd/steps", self._get_debug_spmd_steps,
                  args=("local", "limit")),
            Route("GET", r"/debug/spmd/steps/(?P<seq>[0-9]+)",
                  self._get_debug_spmd_step, args=("local", "limit")),
            Route("GET", r"/debug/slo", self._get_debug_slo),
            Route("GET", r"/debug/admission", self._get_debug_admission),
            Route("GET", r"/debug/oplog", self._get_debug_oplog),
            Route("GET", r"/debug/ingest", self._get_debug_ingest),
            Route("GET", r"/debug/faultpoints", self._get_faultpoints),
            Route("POST", r"/debug/faultpoints", self._post_faultpoints),
            Route("GET", r"/debug/incidents", self._get_debug_incidents),
            Route("GET", r"/debug/incidents/(?P<incident_id>[^/?]+)",
                  self._get_debug_incident),
            Route("GET", r"/debug/threads", self._get_threads),
            Route("GET", r"/debug/pprof/goroutine", self._get_threads),
            Route("POST", r"/debug/pprof/profile/start",
                  self._profile_start),
            Route("POST", r"/debug/pprof/profile/stop", self._profile_stop),
        ]

    # -- handlers ------------------------------------------------------------

    def _home(self, req):
        return {"pilosa_tpu": "a TPU-native bitmap index",
                "version": self.api.info()["version"]}

    def _get_indexes(self, req):
        return self.api.schema()

    def _get_schema(self, req):
        return self.api.schema()

    def _post_schema(self, req):
        self.api.apply_schema(req.json())
        return None

    def _post_index(self, req):
        body = req.json() or {}
        opts = body.get("options", {})
        self.api.create_index(req.params["index"], IndexOptions(
            keys=bool(opts.get("keys", False)),
            track_existence=bool(opts.get("trackExistence", True))))
        return {"success": True}

    def _get_index(self, req):
        idx = self.api.holder.index(req.params["index"])
        if idx is None:
            raise NotFoundError("index not found")
        return {"name": idx.name, "options": idx.options.to_dict()}

    def _delete_index(self, req):
        self.api.delete_index(req.params["index"])
        return {"success": True}

    def _post_field(self, req):
        body = req.json() or {}
        options = field_options_from_json(body.get("options"))
        self.api.create_field(req.params["index"], req.params["field"],
                              options)
        return {"success": True}

    def _delete_field(self, req):
        self.api.delete_field(req.params["index"], req.params["field"])
        return {"success": True}

    def _admission_headers(self, req):
        """(absolute_deadline, query_class) parsed from the request's
        `X-Request-Deadline` / `X-Query-Class` headers — THE deadline
        entry point (fan-out legs re-enter here too, so a coordinator's
        forwarded budget is re-anchored against this node's clock).
        Malformed values are a 400 at the edge; an already-negative
        budget still parses (api.query answers it with 504)."""
        hdrs = getattr(req, "headers", None)
        qclass = None
        raw = hdrs.get("X-Query-Class") if hdrs is not None else None
        if raw is not None:
            qclass = raw.strip().lower()
            if qclass not in ("interactive", "batch", "internal"):
                raise ApiError(
                    "X-Query-Class must be interactive|batch|internal, "
                    f"got {raw!r}")
        deadline = None
        raw = hdrs.get("X-Request-Deadline") if hdrs is not None else None
        if raw is not None:
            from . import admission as admission_mod

            try:
                remaining = admission_mod.parse_deadline(raw)
            except ValueError as e:
                raise ApiError(
                    f"invalid X-Request-Deadline {raw!r}: {e}") from e
            deadline = time.monotonic() + remaining
        return deadline, qclass

    def _post_query(self, req):
        from ..exec import ExecOptions

        deadline, qclass = self._admission_headers(req)
        if req.content_type.startswith("application/x-protobuf"):
            # protobuf data plane, wire-compatible with the reference's
            # QueryRequest/QueryResponse (encoding/proto/proto.go)
            from .. import encoding

            q = encoding.decode_query_request(req.body)
            options = ExecOptions(
                remote=q["remote"], column_attrs=q["column_attrs"],
                exclude_row_attrs=q["exclude_row_attrs"],
                exclude_columns=q["exclude_columns"])
            try:
                results = self.api.query(
                    req.params["index"], q["query"], shards=q["shards"],
                    options=options, deadline=deadline,
                    query_class=qclass)
                attr_sets = self.api.column_attr_sets(
                    req.params["index"], results) \
                    if q["column_attrs"] else None
                body = encoding.encode_query_response(
                    results, column_attr_sets=attr_sets)
            except (ServiceUnavailableError, GatewayTimeoutError):
                # shed/unready/deadline must stay HTTP-visible: the
                # coordinator keys on the status code and the
                # Retry-After / X-Pilosa-Shed headers, which an embedded
                # proto error string would destroy
                raise
            except ApiError as e:
                body = encoding.encode_query_response([], err=str(e))
            return RawResponse(body, encoding.CONTENT_TYPE_PROTOBUF)

        pql = req.body.decode("utf-8")
        shards = None
        if "shards" in req.query:
            shards = [int(s) for s in req.query["shards"][0].split(",") if s]
        column_attrs = \
            req.query.get("columnAttrs", ["false"])[0] == "true"
        want_profile = req.query.get("profile", ["false"])[0] == "true"
        # ?explain=true|plan plans without executing; ?explain=analyze
        # executes and grafts actual costs (see exec/plan.py)
        explain = None
        raw_explain = req.query.get("explain", [None])[0]
        if raw_explain is not None:
            explain = {"true": "plan", "plan": "plan",
                       "analyze": "analyze",
                       "false": None}.get(raw_explain.lower(), "bad")
            if explain == "bad":
                raise ApiError(
                    f"explain must be true|plan|analyze, "
                    f"got {raw_explain!r}")
        options = ExecOptions(
            remote=req.query.get("remote", ["false"])[0] == "true",
            column_attrs=column_attrs,
            exclude_columns=req.query.get(
                "excludeColumns", ["false"])[0] == "true",
            exclude_row_attrs=req.query.get(
                "excludeRowAttrs", ["false"])[0] == "true",
            profile=want_profile, explain=explain)
        results = self.api.query(
            req.params["index"], pql, shards=shards, options=options,
            deadline=deadline, query_class=qclass)
        out = {"results": [result_to_json(r) for r in results]}
        if self.api.serving_stale():
            # degradation ladder at STALE_OK+: reads may lag the ingest
            # staleness bound — marked so clients can tell
            out["stale"] = True
        if explain is not None:
            from ..exec import plan as plan_mod

            # the executor stashed this thread's plan envelope
            out["plan"] = plan_mod.take_last()
        if want_profile:
            from ..utils import profile as profile_mod

            # api.query stashed the finished profile on this thread
            out["profile"] = profile_mod.take_last()
        if column_attrs:
            # reference: QueryResponse "columnAttrs" JSON field
            out["columnAttrs"] = self.api.column_attr_sets(
                req.params["index"], results)
        return out

    def _post_query_batch(self, req):
        """Batched query endpoint: a JSON list of PQL strings executed as
        one fused dispatch (same vmapped executor path as the coalescer).
        Body: {"queries": ["Count(Row(f=1))", ...]} — or a bare JSON
        list. Per-query error isolation: each slot of "results" is
        either {"results": [...], "batch": n} or {"error": "..."}."""
        import json

        try:
            body = json.loads(req.body.decode("utf-8"))
        except Exception as e:
            raise ApiError(f"invalid JSON body: {e}") from e
        if isinstance(body, dict):
            queries = body.get("queries")
        else:
            queries = body
        if not isinstance(queries, list) \
                or not all(isinstance(q, str) for q in queries):
            raise ApiError(
                'body must be {"queries": [<pql>, ...]} or a JSON list '
                "of PQL strings")
        shards = None
        if "shards" in req.query:
            shards = [int(s) for s in req.query["shards"][0].split(",") if s]
        out = []
        for results, error, bsize, _fp in self.api.query_batch(
                req.params["index"], queries, shards=shards):
            if error is not None:
                out.append({"error": str(error)})
            else:
                out.append({"results": [result_to_json(r)
                                        for r in results],
                            "batch": bsize})
        return {"results": out}

    def _post_import(self, req):
        index, field = req.params["index"], req.params["field"]
        clear = req.query.get("clear", ["false"])[0] == "true"
        remote = req.query.get("remote", ["false"])[0] == "true"
        if req.content_type.startswith("application/x-protobuf"):
            # Stock-client wire (reference: handlePostImport
            # http/handler.go:1076 — protobuf-ONLY there; we accept JSON
            # too for our internal client). Message chosen by field
            # type, timestamps are unix NANOseconds (api.go:1010
            # time.Unix(0, ts)); responds with ImportResponse bytes on
            # success. Failures return non-proto error bodies with a
            # non-200 status — matching the reference, whose handler
            # also http.Error()s plain text and only marshals
            # ImportResponse on the success path.
            import datetime as _dt

            from ..encoding import pilosa_pb2 as _pb

            from ..core.field import FIELD_TYPE_INT

            fld = self.api._field(index, field)  # 404 on unknown
            if fld.type == FIELD_TYPE_INT:
                msg = _pb.ImportValueRequest()
                msg.ParseFromString(req.body)
                self.api.import_values(
                    index, field, list(msg.ColumnIDs), list(msg.Values),
                    remote=remote, clear=clear,
                    column_keys=list(msg.ColumnKeys) or None)
            else:
                msg = _pb.ImportRequest()
                msg.ParseFromString(req.body)
                timestamps = None
                if any(msg.Timestamps):
                    timestamps = [
                        _dt.datetime.fromtimestamp(
                            ts / 1e9, _dt.timezone.utc).replace(tzinfo=None)
                        if ts else None for ts in msg.Timestamps]
                self.api.import_bits(
                    index, field, list(msg.RowIDs), list(msg.ColumnIDs),
                    timestamps=timestamps, clear=clear, remote=remote,
                    row_keys=list(msg.RowKeys) or None,
                    column_keys=list(msg.ColumnKeys) or None)
            return RawResponse(
                _pb.ImportResponse(Err="").SerializeToString(),
                "application/x-protobuf")
        body = req.json()
        if body is None:
            raise ApiError("import requires a JSON body")
        if "values" in body:
            changed = self.api.import_values(
                index, field, body.get("columnIDs", []), body["values"],
                remote=remote, clear=clear,
                column_keys=body.get("columnKeys"))
        else:
            timestamps = body.get("timestamps")
            if timestamps is not None:
                timestamps = [
                    timeq.parse_time(t) if t else None for t in timestamps]
            changed = self.api.import_bits(
                index, field, body.get("rowIDs", []),
                body.get("columnIDs", []), timestamps=timestamps,
                clear=clear, remote=remote,
                row_keys=body.get("rowKeys"),
                column_keys=body.get("columnKeys"))
        return {"changed": changed}

    def _post_import_roaring(self, req):
        clear = req.query.get("clear", ["false"])[0] == "true"
        view = req.query.get("view", ["standard"])[0]
        remote = req.query.get("remote", ["false"])[0] == "true"
        if req.content_type.startswith("application/x-protobuf"):
            # Stock-client wire (reference: handlePostImportRoaring
            # http/handler.go — protobuf ImportRoaringRequest with one
            # blob per view; empty view name means standard. We keep the
            # raw-bytes + ?view= form for the internal client.)
            from ..encoding import pilosa_pb2 as _pb

            msg = _pb.ImportRoaringRequest()
            msg.ParseFromString(req.body)
            for v in msg.views:
                # the proto response carries only Err (reference shape);
                # the change count is JSON-path-only
                self.api.import_roaring(
                    req.params["index"], req.params["field"],
                    int(req.params["shard"]), v.Data,
                    clear=bool(msg.Clear),
                    view=v.Name or "standard", remote=remote)
            return RawResponse(
                _pb.ImportResponse(Err="").SerializeToString(),
                "application/x-protobuf")
        changed = self.api.import_roaring(
            req.params["index"], req.params["field"],
            int(req.params["shard"]), req.body, clear=clear, view=view,
            remote=remote)
        return {"changed": changed}

    def _get_export(self, req):
        index = req.query.get("index", [None])[0]
        field = req.query.get("field", [None])[0]
        shard = req.query.get("shard", ["0"])[0]
        if not index or not field:
            raise ApiError("index and field query params required")
        csv_text = self.api.export_csv(index, field, int(shard))
        return RawResponse(csv_text.encode(), "text/csv")

    def _get_status(self, req):
        # ?observability=true: the coordinator additionally aggregates
        # every peer's HBM/kernel summary (short-timeout client fetches)
        return self.api.status(
            include_remote_observability=(
                self._q1(req, "observability", "false") == "true"))

    def _get_healthz(self, req):
        """Liveness: the process is up and serving HTTP. Deliberately
        ignores the device link — a dead tunnel needs draining
        (/readyz), not a restart loop."""
        return {"status": "ok"}

    def _get_readyz(self, req):
        """Readiness, gated on the device-link prober: LIVE, DEGRADED,
        and DISABLED (no prober configured) serve; DOWN answers 503 +
        Retry-After so load balancers drain the node until canary
        probes recover."""
        from ..utils import devhealth

        state = devhealth.state()
        if state == devhealth.DOWN:
            raise ServiceUnavailableError(
                f"not ready: device link {state}",
                retry_after=devhealth.retry_after_seconds())
        return {"status": "ok", "device_link": state}

    def _get_info(self, req):
        return self.api.info()

    def _get_version(self, req):
        return {"version": self.api.info()["version"]}

    def _get_shards_max(self, req):
        return self.api.shards_max()

    def _get_nodes(self, req):
        return self.api.hosts()

    def _get_index_shards(self, req):
        return self.api.index_shards(req.params["index"])

    def _get_shard_fragments(self, req):
        return self.api.shard_fragments(
            req.params["index"], req.params["shard"])

    def _post_message(self, req):
        self.api.receive_message(req.body)
        return None

    def _post_spmd_step(self, req):
        import json as _json

        value = self.api.spmd_step(_json.loads(req.body.decode()))
        return {"value": value}

    def _post_spmd_stream(self, req):
        """Streamed step announcement (serve-mode on): enqueue + ack —
        the peer's stream runner executes the collective out-of-band,
        which is what lets the coordinator pipeline the next step."""
        import json as _json

        return self.api.spmd_stream(_json.loads(req.body.decode()))

    def _post_spmd_validate(self, req):
        import json as _json

        if self.api.spmd is None:
            return {"ok": False, "reason": "spmd mode not enabled"}
        return self.api.spmd.validate(_json.loads(req.body.decode()))

    def _post_spmd_initiate(self, req):
        """Non-coordinator nodes forward eligible calls here for collective
        step initiation (the coordinator is the single step initiator)."""
        import json as _json

        if self.api.spmd is None:
            return {"used": False}
        return self.api.spmd.initiate(_json.loads(req.body.decode()))

    def _get_spmd_stats(self, req):
        if self.api.spmd is None:
            return {"steps": 0, "initialized": False}
        return self.api.spmd.stats()

    def _q1(self, req, key, default=None):
        return req.query.get(key, [default])[0]

    def _get_fragment_nodes(self, req):
        """Owner nodes of one shard (reference: http/handler.go:311
        handleGetFragmentNodes — a stock internal client resolves fragment
        placement through this exact path)."""
        shard = self._q1(req, "shard")
        if shard is None or not shard.isdigit():
            raise ApiError("shard should be an unsigned integer")
        index = self._q1(req, "index")
        if not index:
            raise ApiError("index required")
        return self.api.shard_nodes(index, int(shard))

    def _delete_remote_available_shard(self, req):
        """(reference: http/handler.go:316 handleDeleteRemoteAvailableShard)"""
        self.api.delete_available_shard(
            req.params["index"], req.params["field"],
            int(req.params["shard"]))
        return {"success": True}

    def _get_fragment_blocks(self, req):
        return self.api.fragment_blocks(
            self._q1(req, "index"), self._q1(req, "field"),
            self._q1(req, "view", "standard"), self._q1(req, "shard", "0"))

    def _get_fragment_block_data(self, req):
        return self.api.fragment_block_data(
            self._q1(req, "index"), self._q1(req, "field"),
            self._q1(req, "view", "standard"), self._q1(req, "shard", "0"),
            self._q1(req, "block", "0"))

    def _get_fragment_data(self, req):
        data = self.api.fragment_data(
            self._q1(req, "index"), self._q1(req, "field"),
            self._q1(req, "view", "standard"), self._q1(req, "shard", "0"))
        return RawResponse(data, "application/octet-stream")

    def _get_translate_data(self, req):
        return self.api.translate_data(
            self._q1(req, "index"), self._q1(req, "field", ""),
            int(self._q1(req, "offset", "0")))

    def _post_translate_data(self, req):
        """POST sibling of the GET feed (reference: handler.go routes both
        methods to handleGetTranslateData): replication readers that carry
        the cursor in a JSON body instead of the query string."""
        body = req.json() or {}
        return self.api.translate_data(
            body.get("index", ""), body.get("field", ""),
            int(body.get("offset", 0)))

    def _post_translate_keys(self, req):
        body = req.json() or {}
        return self.api.translate_keys_create(
            body.get("index", ""), body.get("field", ""),
            body.get("keys", []))

    def _get_attr_blocks(self, req):
        return self.api.attr_blocks(
            self._q1(req, "index"), self._q1(req, "field", ""))

    def _get_attr_block_data(self, req):
        return self.api.attr_block_data(
            self._q1(req, "index"), self._q1(req, "field", ""),
            int(self._q1(req, "block", "0")))

    def _post_index_attr_diff(self, req):
        """(reference: handler.go:312 handlePostIndexAttrDiff)"""
        body = req.json() or {}
        return self.api.attr_diff(
            req.params["index"], "", body.get("blocks", []))

    def _post_field_attr_diff(self, req):
        """(reference: handler.go:315 handlePostFieldAttrDiff)"""
        body = req.json() or {}
        return self.api.attr_diff(
            req.params["index"], req.params["field"],
            body.get("blocks", []))

    def _recalculate_caches(self, req):
        self.api.recalculate_caches()
        return None

    # -- resize admin (reference: /cluster/resize/* api.go:1193-1267) ---------

    def _resize_add_node(self, req):
        return self.api.resize_add_node(req.json() or {})

    def _resize_remove_node(self, req):
        body = req.json() or {}
        return self.api.resize_remove_node(body.get("id"))

    def _resize_abort(self, req):
        return self.api.resize_abort()

    def _resize_status(self, req):
        return self.api.resize_status()

    def _set_coordinator(self, req):
        body = req.json() or {}
        return self.api.set_coordinator(body.get("id"))

    def _get_metrics(self, req):
        from ..utils.stats import registry_of

        return RawResponse(registry_of(self.stats).prometheus_text().encode(),
                           "text/plain; version=0.0.4")

    def _get_debug_vars(self, req):
        """expvar-style JSON metrics (reference: /debug/vars route
        http/handler.go:281), plus the stacked-evaluator cache gauges."""
        import json as _json

        from ..utils.stats import registry_of

        out = _json.loads(registry_of(self.stats).expvar_json())
        ex = getattr(self.api, "executor", None)
        local = getattr(ex, "local", ex)  # ClusterExecutor wraps Executor
        if hasattr(local, "stacked_stats"):
            out["stacked"] = local.stacked_stats()
        if self.api.spmd is not None:
            out["spmd"] = self.api.spmd.stats()
        from ..utils import workpool

        out["workpool"] = workpool.get_pool().stats()
        return RawResponse(_json.dumps(out).encode(), "application/json")

    def _get_debug_queries(self, req):
        """Recent query profiles, newest first (the bounded ring every
        profiled query — ?profile=true or long-query-time — lands in)."""
        from ..utils import profile as profile_mod

        return profile_mod.recent()

    def _get_debug_plans(self, req):
        """Misestimated EXPLAIN ANALYZE plans, newest first (the ring
        exec/plan.py retains when actual cost deviates from the estimate
        past the configured factor), plus the cumulative flag counters.
        ?limit=0 returns counters only — the coordinator's /status
        observability roll-up polls peers that way."""
        from ..exec import plan as plan_mod

        limit = self._q1(req, "limit")
        out = dict(plan_mod.stats())
        out["plans"] = plan_mod.recent(
            limit=int(limit) if limit is not None else None)
        return out

    def _get_debug_traces(self, req):
        """Dump of the retained span ring when an InMemoryTracer is
        installed (--tracing memory); tells you how to enable it when
        the zero-overhead nop default is active."""
        from ..utils import tracing

        tracer = tracing.get_tracer()
        index_stats = tracing.trace_index().stats()
        if isinstance(tracer, tracing.InMemoryTracer):
            return {"enabled": True, "maxSpans": tracer.max_spans,
                    "traceIndex": index_stats,
                    "spans": tracer.to_dicts()}
        return {"enabled": False, "spans": [],
                "traceIndex": index_stats,
                "hint": "run the server with --tracing memory to retain "
                        "spans; profiled queries land in the trace index "
                        "either way (GET /debug/traces/{trace_id})"}

    def _get_debug_trace(self, req):
        """One assembled trace: this node's spans merged with every
        peer's (skew-corrected) unless ?local=true — the local form is
        what peers serve to the assembling coordinator, so assembly
        cannot recurse."""
        local_only = (self._q1(req, "local", "") or "").lower() \
            in ("1", "true", "yes")
        return self.api.debug_trace(req.params["trace_id"],
                                    local_only=local_only)

    def _get_debug_incidents(self, req):
        """Postmortem bundle listing: trigger counters + every retained
        bundle's metadata ({"enabled": false} without --incident-dir)."""
        from ..utils import incident as incident_mod

        return incident_mod.snapshot()

    def _get_debug_incident(self, req):
        """One postmortem bundle with its files inlined."""
        from ..utils import incident as incident_mod

        mgr = incident_mod.get_manager()
        if mgr is None:
            raise NotFoundError(
                "incident bundles disabled (start with --incident-dir)")
        out = mgr.get(req.params["incident_id"])
        if out is None:
            raise NotFoundError("no such incident bundle")
        return out

    def _get_flightrecorder(self, req):
        """The black-box event ring: the last N things this process did
        (dispatches, cache churn, membership flaps, stalls...). ?limit=
        bounds the tail."""
        from ..utils import flightrec

        limit = self._q1(req, "limit")
        return flightrec.snapshot(limit=int(limit) if limit else None)

    def _local_executor(self):
        ex = getattr(self.api, "executor", None)
        return getattr(ex, "local", ex)  # ClusterExecutor wraps Executor

    def _get_debug_hbm(self, req):
        """HBM ledger: resident stack-cache bytes per (index, field,
        pool), entries ranked by bytes + last-hit age, eviction causes,
        and device memory_stats headroom."""
        local = self._local_executor()
        if not hasattr(local, "hbm_stats"):
            raise NotFoundError("no stacked evaluator on this node")
        return local.hbm_stats(top=int(self._q1(req, "top", "50")))

    def _get_debug_kernels(self, req):
        """Per-kernel-family attribution + XLA cost_analysis per compiled
        program (?costs=false skips the lazy compile on first request)."""
        local = self._local_executor()
        if not hasattr(local, "kernel_stats"):
            raise NotFoundError("no stacked evaluator on this node")
        return local.kernel_stats(
            include_costs=self._q1(req, "costs", "true") != "false")

    def _get_debug_device(self, req):
        """Device-link health: the prober's state machine plus the full
        canary sample ring (?limit= bounds the ring; 0 = summary only)."""
        from ..utils import devhealth

        limit = self._q1(req, "limit")
        return devhealth.snapshot(
            limit=int(limit) if limit is not None else None)

    def _get_debug_dispatch(self, req):
        """Per-kernel dispatch-phase RTT decomposition: where each
        family's round trip goes (lock_wait / transfer_in / compile /
        dispatch_ack / sync)."""
        local = self._local_executor()
        if not hasattr(local, "dispatch_phase_stats"):
            raise NotFoundError("no stacked evaluator on this node")
        return local.dispatch_phase_stats()

    def _get_debug_batching(self, req):
        """Batched-dispatch pipeline stats: coalescer queue depth /
        occupancy histogram / rejects plus fused-dispatch counters."""
        stats = getattr(self.api, "batching_stats", None)
        if stats is None:
            raise NotFoundError("no batching stats on this node")
        return stats()

    #: every debug endpoint with a one-line description — served at
    #: GET /debug so discoverability doesn't depend on the README
    DEBUG_ENDPOINTS = {
        "/debug/vars": "expvar-style counters, gauges, and p50/p99 "
                       "timing summaries",
        "/debug/queries": "recent per-query profiles (span tree + "
                          "dispatch/lock/cache counters), newest first",
        "/debug/traces": "retained raw spans (needs --tracing memory) + "
                         "trace-index stats",
        "/debug/traces/{trace_id}": "ONE assembled trace: coordinator + "
                                    "peer spans merged into a tree with "
                                    "per-node clock-skew correction "
                                    "(?local=true for this node only)",
        "/debug/plans": "misestimated EXPLAIN ANALYZE plans, deduped "
                        "per query fingerprint, newest first",
        "/debug/hbm": "HBM ledger: resident stack-cache bytes per "
                      "(index, field, pool) + device headroom",
        "/debug/kernels": "per-kernel-family dispatch counts, wall, and "
                          "modeled costs",
        "/debug/device": "device-link health: canary probe state "
                         "machine, RTT samples, transitions",
        "/debug/dispatch": "dispatch-phase RTT decomposition (lock_wait "
                           "/ transfer_in / compile / ack / sync)",
        "/debug/batching": "query coalescer: queue depth, batch "
                           "occupancy histogram, rejects, fused-dispatch "
                           "counters",
        "/debug/workload": "query fingerprint table: per-shape counts, "
                           "p50/p99, strategies, misestimates",
        "/debug/heat": "fragment heat vs HBM residency: admission and "
                       "eviction candidates",
        "/debug/optimizer": "adaptive execution engine: calibration "
                            "sources, decision counters, recent "
                            "decisions",
        "/debug/fusion": "whole-plan fusion: mode, program cache "
                         "(fingerprint / compile-ms / hits / last-hit "
                         "age), evictions, fuse-vs-interpret decision "
                         "counters",
        "/debug/spmd": "SPMD mesh serving plane: serve mode, step "
                       "lifecycle counters, stream + observatory state, "
                       "mesh-resident cache (POST switches serve mode)",
        "/debug/spmd/steps": "cross-node collective step timeline: "
                             "per-peer phase walls skew-corrected and "
                             "merged by seq with straggler attribution; "
                             "/debug/spmd/steps/{seq} for one step, "
                             "?local=true for this node's raw ring",
        "/debug/slo": "SLO objectives and multi-window error-budget "
                      "burn rates",
        "/debug/admission": "admission controller: degradation-ladder "
                            "state + transitions, per-class token "
                            "buckets, queue occupancy, rejections",
        "/debug/oplog": "write-ahead oplog: LSNs, checkpoint, fsync "
                        "policy, segment state",
        "/debug/ingest": "streaming ingest engine: delta buffer depth, "
                         "merge counters, deferred oplog watermarks",
        "/debug/flightrecorder": "black-box event ring (dispatches, "
                                 "cache churn, stalls, alerts)",
        "/debug/faultpoints": "fault-injection points (GET state, POST "
                              "to arm)",
        "/debug/incidents": "anomaly-triggered postmortem bundles "
                            "(flightrec + stacks + debug snapshots), "
                            "newest first; /debug/incidents/{id} inlines "
                            "one bundle",
        "/debug/threads": "all-thread stack dump (text)",
        "/debug/pprof/goroutine": "all-thread stack dump",
    }

    def _get_debug_index(self, req):
        """GET /debug: enumerate every debug endpoint (the list outgrew
        the README)."""
        return {"endpoints": [
            {"path": path, "description": desc}
            for path, desc in sorted(self.DEBUG_ENDPOINTS.items())]}

    def _get_debug_workload(self, req):
        """Query fingerprint table: top-K shapes by frequency, total
        wall, and misestimate rate (utils/workload.py). ?top=0 returns
        counters only (the coordinator roll-up shape)."""
        from ..utils import workload as workload_mod

        return workload_mod.table().snapshot(
            top=int(self._q1(req, "top", "20")))

    def _get_debug_heat(self, req):
        """Fragment heat cross-referenced against the HBM ledger:
        hot-but-not-resident (admission candidates) and
        resident-but-cold (eviction candidates)."""
        from ..utils import workload as workload_mod

        local = self._local_executor()
        hbm = local.hbm_stats(top=0) \
            if hasattr(local, "hbm_stats") else None
        return workload_mod.heat().report(
            hbm, top=int(self._q1(req, "top", "50")))

    def _get_debug_optimizer(self, req):
        """Adaptive execution engine state: mode, per-kernel-family
        calibration with sources (ewma|cost_analysis|default), strategy/
        tile/cache/admission decision counters, and the recent-decision
        ring (exec/adaptive.py)."""
        from ..exec import adaptive

        local = self._local_executor()
        return adaptive.snapshot(
            stacked=getattr(local, "_stacked", None))

    def _get_debug_fusion(self, req):
        """Whole-plan fusion state: mode + knobs, the bounded program
        ledger with per-entry compile cost and hit recency, and the
        fuse-vs-interpret decision counters (exec/fusion.py)."""
        from ..exec import fusion

        return fusion.snapshot()

    def _get_debug_spmd(self, req):
        """Mesh serving state: serve mode + mesh shape, per-node step
        lifecycle counters (announced/entered/exited — the wedge
        classifier's input), stream queue state, mesh-resident cache
        stats, and the HTTP data-plane byte counter."""
        return self.api.spmd_debug()

    def _post_debug_spmd(self, req):
        """Runtime serve-mode switch: {"serve_mode": off|on|shadow|http}
        ("http" forces the HTTP fan-out path for A/B benching on the
        same cluster)."""
        body = req.json() or {}
        return self.api.spmd_set_mode(body.get("serve_mode"))

    def _get_debug_spmd_steps(self, req, seq=None):
        """Cross-node collective step timeline: every peer's per-phase
        step walls skew-corrected onto this node's clock and merged by
        seq, with per-phase straggler attribution. ?local=true returns
        this node's raw slice (what the coordinator fans out for — the
        same non-recursing shape as /debug/traces/{id})."""
        local_only = (self._q1(req, "local", "") or "").lower() \
            in ("1", "true", "yes")
        limit = self._q1(req, "limit", None)
        limit = int(limit) if limit is not None else 32
        return self.api.spmd_debug_steps(seq=seq, limit=limit,
                                         local_only=local_only)

    def _get_debug_spmd_step(self, req):
        """One step of the cross-node timeline by sequence number."""
        return self._get_debug_spmd_steps(
            req, seq=int(req.params["seq"]))

    def _get_debug_slo(self, req):
        """SLO objectives with fast/slow-window error-budget burn rates
        (empty objectives list when no --slo is configured)."""
        from ..utils import workload as workload_mod

        return workload_mod.slo().snapshot()

    def _get_debug_admission(self, req):
        """Admission controller snapshot: ladder state, per-class token
        buckets + queue occupancy, calibration, transition history
        ({"enabled": false} when --admission off)."""
        return self.api.admission_stats()

    def _get_debug_oplog(self, req):
        """Durable-oplog summary: segments, checkpoint, replay lag."""
        oplog = getattr(self.api, "oplog", None)
        if oplog is None:
            return {"enabled": False,
                    "hint": "node started without a write-ahead oplog "
                            "(storage oplog=false or no data dir)"}
        out = oplog.summary()
        out["enabled"] = True
        return out

    def _get_debug_ingest(self, req):
        """Streaming ingest engine state: pending delta-buffer depth
        (entries/rows/bytes), per-field breakdown, merge/overflow
        counters, deferred group-commit LSNs (exec/ingest.py)."""
        return self.api.ingest_stats()

    def _get_faultpoints(self, req):
        """Armed fault points + hit counters (crash-test introspection)."""
        from ..utils import faultpoints

        return faultpoints.snapshot()

    def _post_faultpoints(self, req):
        """Arm/disarm fault points on a live server. Body:
        ``{"arm": "<spec>" | ["<spec>", ...], "disarm": "<name>"|"all"}``
        (spec grammar in utils/faultpoints.py). Test-only surface — like
        /debug/pprof it mutates process behavior, so it is part of the
        debug namespace, not the public API."""
        from ..utils import faultpoints

        body = json.loads(req.body.decode() or "{}")
        disarm = body.get("disarm")
        if disarm is not None:
            faultpoints.disarm(None if disarm == "all" else disarm)
        arm = body.get("arm")
        if arm is not None:
            specs = arm if isinstance(arm, list) else [arm]
            try:
                for spec in specs:
                    faultpoints.arm(spec)
            except ValueError as e:
                raise ApiError(str(e)) from e
        return faultpoints.snapshot()

    # -- profiling (reference: /debug/pprof routes http/handler.go:280;
    #    profile.cpu config server/config.go) --------------------------------

    def _get_threads(self, req):
        """Stack dump of every live thread (the goroutine-dump analog)."""
        import sys
        import traceback

        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        out = []
        for ident, frame in frames.items():
            out.append(f"thread {names.get(ident, '?')} ({ident}):")
            out.extend(l.rstrip() for l in traceback.format_stack(frame))
            out.append("")
        return RawResponse("\n".join(out).encode(), "text/plain")

    _profiler_lock = threading.Lock()

    def _profile_start(self, req):
        """Begin a sampling CPU profile of ALL threads (cProfile is
        per-thread and would only see the handler thread that started it;
        a sampler over sys._current_frames covers the whole serving
        path)."""
        interval = float(self._q1(req, "interval", "0.01"))
        with self._profiler_lock:
            if getattr(self, "_profiler", None) is not None:
                raise ApiError("profile already running")
            self._profiler = _SamplingProfiler(interval).start()
        return None

    def _profile_stop(self, req):
        """Stop profiling and return sampled frames, hottest first."""
        with self._profiler_lock:
            prof = getattr(self, "_profiler", None)
            if prof is None:
                raise ApiError("no profile running")
            self._profiler = None
        return RawResponse(prof.stop().encode(), "text/plain")

    # -- server lifecycle ----------------------------------------------------

    def start(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _dispatch(self):
                server.dispatch(self)

            do_GET = do_POST = do_DELETE = do_OPTIONS = _dispatch

        # Stdlib default listen backlog is 5: a burst of concurrent
        # clients (the serving workload the batched count path exists
        # for) overflows it and the kernel RESETS the excess connects.
        # 128 matches common production server defaults.
        class _Server(ThreadingHTTPServer):
            request_queue_size = 128

        self._httpd = _Server((self.host, self.port), Handler)
        if self.tls_cert:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.tls_cert, self.tls_key)
            self._tls_ctx = ctx
            self._stash_keypair()
            # Defer the handshake to the per-connection worker thread
            # (first read); a handshake in accept() would let one stalled
            # client block ALL new connections.
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="pilosa-http", daemon=True)
        self._thread.start()
        return self

    def reload_tls(self):
        """Re-read the certificate/key files into the live TLS context:
        new handshakes serve the new keypair, existing connections are
        untouched (reference: keypairReloader server/tlsconfig.go:68-90,
        which reloads on SIGHUP so operators can rotate certs without a
        restart; the CLI wires SIGHUP to this method). Raises on a bad
        keypair, keeping the old one serving — same policy as the
        reference's maybeReload.

        load_cert_chain mutates the context in stages (cert chain, then
        key, then pair check), so a half-bad rotation could strand the
        LIVE context with new-cert/old-key. Guard rails: validate the
        files in a scratch context first, and if the live load still
        fails (filesystem race between the two loads), restore the
        stashed last-good PEMs into the live context."""
        if not self.tls_cert or self._tls_ctx is None:
            raise RuntimeError("TLS not enabled on this server")
        import ssl

        scratch = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        scratch.load_cert_chain(self.tls_cert, self.tls_key)
        try:
            self._tls_ctx.load_cert_chain(self.tls_cert, self.tls_key)
        except Exception:
            self._restore_last_good_keypair()
            raise
        self._stash_keypair()

    def _stash_keypair(self):
        with open(self.tls_cert, "rb") as f:
            cert_pem = f.read()
        with open(self.tls_key, "rb") as f:
            key_pem = f.read()
        self._tls_last_good = (cert_pem, key_pem)

    def _restore_last_good_keypair(self):
        import tempfile

        if not getattr(self, "_tls_last_good", None):
            return
        cert_pem, key_pem = self._tls_last_good
        with tempfile.NamedTemporaryFile(suffix=".pem") as cf, \
                tempfile.NamedTemporaryFile(suffix=".key") as kf:
            cf.write(cert_pem)
            cf.flush()
            kf.write(key_pem)
            kf.flush()
            self._tls_ctx.load_cert_chain(cf.name, kf.name)

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    @property
    def address(self):
        scheme = "https" if self.tls_cert else "http"
        return f"{scheme}://{self.host}:{self.port}"

    # -- dispatch ------------------------------------------------------------

    def _cors_origin(self, handler):
        """The Access-Control-Allow-Origin value for this request, or None
        (reference: http/handler.go:83-91 OptHandlerAllowedOrigins wraps
        the router in gorilla handlers.CORS; absent the option, no CORS
        headers are emitted and browsers refuse cross-origin reads)."""
        if not self.allowed_origins:
            return None
        origin = handler.headers.get("Origin")
        if origin is None:
            return None
        if "*" in self.allowed_origins:
            return "*"
        return origin if origin in self.allowed_origins else None

    def dispatch(self, handler):
        from ..utils import tracing

        parsed = urlparse(handler.path)
        path = parsed.path.rstrip("/") or "/"
        query = parse_qs(parsed.query)
        length = int(handler.headers.get("Content-Length", 0))
        body = handler.rfile.read(length) if length else b""

        cors = self._cors_origin(handler)
        if handler.command == "OPTIONS":
            # Preflight: answer with the allowed surface, no body.
            handler.send_response(200 if cors else 403)
            if self.allowed_origins:
                # response varies by Origin -> shared caches must key on it
                handler.send_header("Vary", "Origin")
            if cors:
                handler.send_header("Access-Control-Allow-Origin", cors)
                handler.send_header("Access-Control-Allow-Methods",
                                    "GET, POST, DELETE, OPTIONS")
                handler.send_header("Access-Control-Allow-Headers",
                                    "Content-Type")
            handler.send_header("Content-Length", "0")
            handler.end_headers()
            return

        import time as _time

        t0 = _time.perf_counter()
        status, payload, content_type = 404, {"error": "not found"}, \
            "application/json"
        extra_headers = None  # e.g. Retry-After on a 503
        matched = None  # Route whose pattern labels this request's metrics
        trace_id = None  # histogram-exemplar link; the span ends before
        # the timing below is recorded, so capture its id inside the with
        for route in self.routes:
            if route.method != handler.command:
                continue
            m = route.regex.match(path)
            if m is None:
                continue
            matched = route
            if route.args is not None:
                unknown = set(query) - route.args
                if unknown:
                    status, payload = 400, {
                        "error": "invalid query params: "
                                 + ", ".join(sorted(unknown))}
                    break
            req = Request(m.groupdict(), query, body,
                          handler.headers.get("Content-Type", ""),
                          headers=handler.headers)
            # Continue a cross-node trace from incoming headers (reference:
            # http/handler.go:321 extractTracing middleware).
            with tracing.span_from_headers(
                    f"http.{handler.command} {path}", handler.headers,
                    method=handler.command) as span:
                try:
                    result = route.fn(req)
                    if isinstance(result, RawResponse):
                        status, payload, content_type = (
                            200, result.body, result.content_type)
                    else:
                        status, payload = 200, result
                except ApiError as e:
                    status, payload = e.status, {"error": str(e)}
                    extra_headers = e.headers
                except Exception as e:  # internal error
                    status, payload = 500, {"error": str(e)}
                if span is not None:
                    span.set_tag("status", status)
                    trace_id = span.trace_id
            break

        if isinstance(payload, (dict, list)) or payload is None:
            data = json.dumps(payload).encode()
        else:
            data = payload
        # Per-route/per-status request metrics. Tagged with the matched
        # route PATTERN, not the raw path — raw paths (index/field names)
        # are unbounded-cardinality label values. The finally guarantees
        # error responses — 400s, 404s ("unmatched"), 500s, even a write
        # that died on a closed socket — are all counted.
        try:
            handler.send_response(status)
            handler.send_header("Content-Type", content_type)
            handler.send_header("Content-Length", str(len(data)))
            if extra_headers:
                for name, value in extra_headers.items():
                    handler.send_header(name, value)
            if self.allowed_origins:
                handler.send_header("Vary", "Origin")
            if cors:
                handler.send_header("Access-Control-Allow-Origin", cors)
            handler.end_headers()
            handler.wfile.write(data)
        finally:
            tags = {"route": matched.pattern if matched else "unmatched",
                    "method": handler.command, "status": str(status)}
            self.stats.timing(
                "http_request_seconds", _time.perf_counter() - t0, tags,
                trace_id=trace_id)
            if status >= 400:
                self.stats.count("http_errors", 1, tags)
            if status >= 500:
                from ..utils import flightrec

                flightrec.record(
                    "http.5xx", route=tags["route"],
                    method=handler.command, status=status)


class _SamplingProfiler:
    """Wall-clock stack sampler across every thread (py-spy style).
    `self` = samples where the frame is the leaf; `cum` = samples where it
    appears anywhere in a stack."""

    def __init__(self, interval=0.01):
        self.interval = max(interval, 0.001)
        self.self_counts = {}
        self.cum_counts = {}
        self.n_samples = 0
        self._stop_evt = threading.Event()
        self._thread = None

    def _sample(self):
        import sys

        me = threading.get_ident()
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            self.n_samples += 1
            leaf = True
            seen = set()
            while frame is not None:
                code = frame.f_code
                key = f"{code.co_filename}:{frame.f_lineno} {code.co_name}"
                if leaf:
                    self.self_counts[key] = self.self_counts.get(key, 0) + 1
                    leaf = False
                if key not in seen:  # count recursion once per stack
                    seen.add(key)
                    self.cum_counts[key] = self.cum_counts.get(key, 0) + 1
                frame = frame.f_back

    def _run(self):
        while not self._stop_evt.wait(self.interval):
            self._sample()

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name="pilosa-profiler", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop_evt.set()
        self._thread.join(timeout=5)
        lines = [f"samples: {self.n_samples} "
                 f"(interval {self.interval * 1000:.0f}ms)",
                 "", "self  cum   frame"]
        ranked = sorted(self.self_counts.items(),
                        key=lambda kv: -kv[1])[:50]
        for key, n in ranked:
            lines.append(f"{n:>5} {self.cum_counts.get(key, 0):>5} {key}")
        return "\n".join(lines) + "\n"


class Request:
    __slots__ = ("params", "query", "body", "content_type", "headers")

    def __init__(self, params, query, body, content_type="", headers=None):
        self.params = params
        self.query = query
        self.body = body
        self.content_type = content_type
        # the raw http.client message (dict-like, case-insensitive) —
        # None in tests that build Requests by hand
        self.headers = headers

    def json(self):
        if not self.body:
            return None
        return json.loads(self.body.decode("utf-8"))


class RawResponse:
    __slots__ = ("body", "content_type")

    def __init__(self, body, content_type):
        self.body = body
        self.content_type = content_type
