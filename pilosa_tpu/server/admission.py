"""Cost-aware admission control, deadline propagation, and the
degradation ladder (ROADMAP item 5: overload must degrade gracefully).

The serving substrate this consumes was already built and idle:

- exec/plan.py's cost model prices a query in device-milliseconds from
  host-side metadata only — zero dispatches — so admission can charge
  a GroupBy 100x what it charges a Count BEFORE either touches the
  dispatch lock.
- utils/workload.py's SLO burn engine fires `slo.burn_alert` events
  that, until now, nothing acted on.
- utils/devhealth.py's prober knows the device link is DOWN long
  before a queued query would find out.

Three mechanisms, one controller:

1. **Classes + token buckets.** Every query lands in one of three
   classes — interactive (default for reads), batch (writes, exports,
   anything header-marked), internal (health/debug traffic) — each
   with its own token bucket holding *device-milliseconds*. A bucket
   refills at `capacity_ms_per_s * share` and is debited the priced
   cost of each admitted query, so one expensive GroupBy cannot starve
   a thousand cheap Counts and a write flood cannot starve reads.
   Estimates are calibrated against measured walls (EWMA) so drifting
   cost-model numbers do not silently over/under-admit.

2. **Bounded per-class wait queue.** A query whose bucket is dry waits
   (FIFO within its class) in front of the dispatch lock — bounded:
   past `queue_depth` waiters the request is rejected immediately with
   503 + Retry-After sized to the bucket's refill deficit. A waiter
   whose deadline lapses in queue is dropped at wake-up — it never
   reaches the dispatch lock (tests pin the stacked dispatch counters
   flat).

3. **Degradation ladder.** NORMAL → SHED_BATCH → STALE_OK → LIFEBOAT,
   driven by the SLO burn engine and devhealth:

       NORMAL      everything admitted per bucket
       SHED_BATCH  batch is queued-only: it waits even when its bucket
                   has tokens, and the ingest engine defers interval
                   merges (overflow still forces one)
       STALE_OK    + reads may serve resident stacks past the ingest
                   staleness bound; responses are marked "stale"
       LIFEBOAT    only internal traffic and interactive *reads*
                   admitted; writes and batch shed outright

   Transitions are edge-triggered into the flight recorder
   (`admission.state`) and exported as the `admission_state` gauge;
   GET /debug/admission serves the full picture.

Default OFF: `--admission off` never constructs a controller, the
query path's only residue is one `is None` check, and the legacy
path stays byte-identical (the repo's escape-hatch convention, like
coalesce-window=0 and ingest-merge-interval=0).
"""

import threading
import time

# ------------------------------------------------------------- classes

INTERACTIVE = "interactive"
BATCH = "batch"
INTERNAL = "internal"
CLASSES = (INTERACTIVE, BATCH, INTERNAL)

# ------------------------------------------------------- ladder states

NORMAL = "NORMAL"
SHED_BATCH = "SHED_BATCH"
STALE_OK = "STALE_OK"
LIFEBOAT = "LIFEBOAT"
STATES = (NORMAL, SHED_BATCH, STALE_OK, LIFEBOAT)
STATE_RANK = {s: i for i, s in enumerate(STATES)}

#: device-milliseconds refilled per wall second with no --admission-
#: capacity override: one device-second of modeled kernel wall per
#: second (the cost model prices in single-device dispatch walls)
DEFAULT_CAPACITY_MS_PER_S = 1000.0
#: per-class slices of that capacity; interactive gets the majority so
#: a write flood can never starve reads (the failure mode that
#: motivates per-class buckets over one global one)
DEFAULT_SHARES = {INTERACTIVE: 0.6, BATCH: 0.3, INTERNAL: 0.1}
#: burst: a bucket holds at most this many seconds of refill, so an
#: idle class can absorb a spike without banking unbounded credit
BURST_SECONDS = 2.0
#: waiters per class past which admission rejects immediately
DEFAULT_QUEUE_DEPTH = 64
#: longest a dry-bucket waiter parks before giving up with 503 (a
#: request deadline shortens it; nothing lengthens it)
DEFAULT_QUEUE_TIMEOUT = 5.0
#: priced cost when the planner errors out mid-estimate — small, so a
#: pricing bug degrades to near-legacy admission, not an outage
FALLBACK_COST_MS = 1.0
#: ladder holds a rung at least this long before stepping DOWN (up is
#: immediate); flapping between NORMAL and SHED_BATCH every sample
#: would churn clients worse than either state
LADDER_HOLD_SECONDS = 10.0
#: ladder re-evaluation cadence on the serving path
LADDER_SAMPLE_INTERVAL = 1.0
#: burn multiples (of the engine's alert threshold) that escalate past
#: SHED_BATCH — see _target_state
STALE_BURN_FACTOR = 2.0
LIFEBOAT_BURN_FACTOR = 4.0


class Rejected(Exception):
    """Admission shed this request (maps to 503 + Retry-After)."""

    def __init__(self, message, retry_after, qclass):
        super().__init__(message)
        self.retry_after = retry_after
        self.qclass = qclass


class Expired(Exception):
    """The request deadline lapsed before dispatch (maps to 504)."""


def parse_deadline(raw, now=None):
    """`X-Request-Deadline` header -> seconds of budget remaining.

    Accepts a bare number (seconds, e.g. "0.25"), a duration with
    units ("250ms", "2s", "1m30s"), or "@<unix-seconds>" for an
    absolute epoch deadline. Returns the remaining budget in seconds —
    zero or negative means expired-on-arrival (the caller answers 504
    without dispatching). Raises ValueError on anything unparseable
    (the caller answers 400)."""
    s = str(raw).strip()
    if not s:
        raise ValueError("empty deadline")
    if s.startswith("@"):
        if now is None:
            now = time.time()
        return float(s[1:]) - now
    try:
        return float(s)
    except ValueError:
        pass
    from ..cli import parse_duration

    return float(parse_duration(s))


def classify(header=None, query=None, path_internal=False):
    """Request class: the `X-Query-Class` header wins when present
    (validated upstream), else PQL shape — writes and exports are
    batch, /debug and health probes internal, reads interactive."""
    if header:
        return header
    if path_internal:
        return INTERNAL
    if query is not None:
        try:
            if any(c.writes() for c in query.calls):
                return BATCH
        except Exception:  # noqa: BLE001 — unparseable shapes default
            pass
    return INTERACTIVE


class TokenBucket:
    """Device-millisecond budget for one class. Not thread-safe on its
    own — the controller's lock covers every call."""

    def __init__(self, rate_ms_per_s, burst_seconds=BURST_SECONDS):
        self.rate = float(rate_ms_per_s)
        self.burst = self.rate * burst_seconds
        self.tokens = self.burst  # start full: no cold-start shedding
        self._at = time.monotonic()

    def refill(self, now):
        self.tokens = min(self.burst,
                          self.tokens + (now - self._at) * self.rate)
        self._at = now

    def try_debit(self, cost_ms, now):
        self.refill(now)
        if self.tokens >= cost_ms:
            self.tokens -= cost_ms
            return True
        return False

    def credit(self, ms):
        """Refund over-charged estimate (never past the burst cap)."""
        self.tokens = min(self.burst, self.tokens + ms)

    def deficit_seconds(self, cost_ms):
        """Refill time until `cost_ms` fits — the honest Retry-After."""
        if self.rate <= 0:
            return DEFAULT_QUEUE_TIMEOUT
        return max(0.0, (cost_ms - self.tokens) / self.rate)


# live controllers (normally one per process) — bench attempt tagging
_REGISTRY = []


def mode():
    """'off' or 'on state=<ladder rung>' — bench attempt tagging:
    serving numbers are only comparable across runs measured under the
    same admission policy and degradation rung."""
    if not _REGISTRY:
        return "off"
    return f"on state={_REGISTRY[0].state}"


class AdmissionController:
    """The QoS gate in front of the executor. One per API; every
    method is thread-safe. See the module docstring for the model."""

    def __init__(self, capacity_ms_per_s=None, shares=None,
                 queue_depth=DEFAULT_QUEUE_DEPTH,
                 queue_timeout=DEFAULT_QUEUE_TIMEOUT, logger=None):
        self.capacity = float(capacity_ms_per_s
                              or DEFAULT_CAPACITY_MS_PER_S)
        self.shares = dict(DEFAULT_SHARES)
        if shares:
            self.shares.update(shares)
        self.queue_depth = int(queue_depth)
        self.queue_timeout = float(queue_timeout)
        self.logger = logger
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.buckets = {
            c: TokenBucket(self.capacity * self.shares[c])
            for c in CLASSES}
        self._waiting = {c: 0 for c in CLASSES}
        self._queue = {c: [] for c in CLASSES}  # ticket FIFO per class
        self._ticket = 0  # monotone ticket numbers, FIFO within a class
        self._closed = False
        # pricing calibration: EWMA of measured_wall / priced_cost for
        # completed queries; multiplies future debits so a cost model
        # that under-prices by 3x doesn't over-admit by 3x
        self._calibration = 1.0
        self._calibration_n = 0
        # ladder
        self.state = NORMAL
        self.state_since = time.monotonic()
        self._ladder_checked = 0.0
        self.transitions = []  # bounded ring of {from,to,reason,at}
        # counters (under _lock)
        self.admitted = {c: 0 for c in CLASSES}
        self.rejected = {c: 0 for c in CLASSES}
        self.queued = {c: 0 for c in CLASSES}
        self.expired = {c: 0 for c in CLASSES}
        self.shed_by_state = {s: 0 for s in STATES}
        from ..utils.stats import global_stats

        global_stats.gauge_fn(
            "admission_state",
            lambda: STATE_RANK.get(self.state, 0))
        _REGISTRY.append(self)

    # -- pricing -----------------------------------------------------------

    def price(self, executor, idx, query, shards, opt):
        """Priced cost of one query in device-milliseconds, from the
        EXPLAIN cost model — host-side metadata only, zero dispatches
        (the planner's contract; tests pin the dispatch-counter delta
        at 0 across a price() call). Any pricing failure degrades to a
        small flat cost rather than failing the query."""
        try:
            from ..exec import plan as plan_mod

            local = getattr(executor, "local", executor)
            nodes = plan_mod.Planner(local).plan_query(
                idx, query.calls, shards, opt)
            wall = 0.0
            for root in nodes:
                for node in root.walk():
                    wall += node.estimate.get("kernel_wall_seconds", 0.0)
            return max(wall * 1000.0, FALLBACK_COST_MS)
        except Exception:  # noqa: BLE001 — pricing must never 500
            return FALLBACK_COST_MS

    # -- admission ---------------------------------------------------------

    def admit(self, qclass, cost_ms, deadline=None, is_write=False,
              now=None):
        """Admit, queue, or shed one request. Returns a ticket (pass it
        to note_done) or raises Rejected / Expired. `deadline` is an
        absolute time.monotonic() instant."""
        if qclass not in CLASSES:
            qclass = INTERACTIVE
        if now is None:
            now = time.monotonic()
        self.maybe_update_ladder(now)
        with self._lock:
            state = self.state
            rank = STATE_RANK[state]
            # ladder gating before any token math: LIFEBOAT serves only
            # internal traffic and interactive reads
            if rank >= STATE_RANK[LIFEBOAT] and (
                    is_write or qclass == BATCH):
                self.rejected[qclass] += 1
                self.shed_by_state[state] += 1
                raise Rejected(
                    f"admission state {state}: only internal and "
                    "interactive reads served", LADDER_HOLD_SECONDS,
                    qclass)
            bucket = self.buckets[qclass]
            # cap the debit at the bucket's burst: a cost above it could
            # never be granted (refill tops out at burst), so without the
            # cap one over-priced — or legitimately huge — request waits
            # out the queue timeout instead of draining the bucket whole
            cost = min(cost_ms * self._calibration, bucket.burst)
            # SHED_BATCH+: batch is queued-only — no immediate grants,
            # even with tokens banked; it parks below and only drains
            # once the ladder steps back down
            queued_only = (qclass == BATCH
                           and rank >= STATE_RANK[SHED_BATCH])
            if not queued_only and bucket.try_debit(cost, now):
                self.admitted[qclass] += 1
                return {"class": qclass, "cost_ms": cost_ms,
                        "debited_ms": cost, "t0": now}
            # dry bucket (or batch under shed): bounded FIFO wait
            if self._waiting[qclass] >= self.queue_depth:
                self.rejected[qclass] += 1
                retry = bucket.deficit_seconds(cost) + 1.0
                raise Rejected(
                    f"admission queue full for class {qclass} "
                    f"({self.queue_depth} waiting)", retry, qclass)
            self._ticket += 1
            my_turn = self._ticket
            self._waiting[qclass] += 1
            self._queue[qclass].append(my_turn)
            self.queued[qclass] += 1
            try:
                give_up = now + self.queue_timeout
                if deadline is not None:
                    give_up = min(give_up, deadline)
                while True:
                    wait_now = time.monotonic()
                    # queue pop: an expired waiter is DROPPED here —
                    # it never reaches the dispatch lock
                    if deadline is not None and wait_now >= deadline:
                        self.expired[qclass] += 1
                        raise Expired(
                            f"deadline lapsed after "
                            f"{wait_now - now:.3f}s in admission queue")
                    if self._closed:
                        raise Rejected("admission controller shut down",
                                       1.0, qclass)
                    state = self.state
                    queued_only = (qclass == BATCH and STATE_RANK[state]
                                   >= STATE_RANK[SHED_BATCH])
                    if not queued_only and self._head_of_class(
                            qclass, my_turn) and bucket.try_debit(
                                cost, wait_now):
                        self.admitted[qclass] += 1
                        return {"class": qclass, "cost_ms": cost_ms,
                                "debited_ms": cost, "t0": now}
                    if wait_now >= give_up:
                        self.rejected[qclass] += 1
                        retry = bucket.deficit_seconds(cost) + 1.0
                        raise Rejected(
                            f"admission wait timed out for class "
                            f"{qclass}", retry, qclass)
                    # wake at the earliest of: refill covers the cost,
                    # give-up, deadline — bounded so a lost notify
                    # can't park a handler forever
                    self._cond.wait(min(
                        0.05 + bucket.deficit_seconds(cost),
                        max(give_up - wait_now, 0.001)))
            finally:
                self._waiting[qclass] -= 1
                self._queue[qclass].remove(my_turn)
                self._cond.notify_all()

    def _head_of_class(self, qclass, my_turn):
        """FIFO within a class: only the oldest live waiter may debit,
        so a lucky late arrival can't starve an earlier one forever.
        Caller holds the lock."""
        q = self._queue[qclass]
        return not q or q[0] == my_turn

    def note_done(self, ticket, wall_seconds):
        """Completion hook: calibrate pricing against the measured
        wall and refund gross over-charges so capacity isn't wasted on
        bad estimates."""
        if ticket is None:
            return
        measured_ms = max(wall_seconds * 1000.0, 0.01)
        with self._lock:
            est = max(ticket.get("cost_ms", FALLBACK_COST_MS), 0.01)
            ratio = min(max(measured_ms / est, 0.01), 100.0)
            # slow EWMA: one wild outlier shouldn't swing admission
            alpha = 0.05
            self._calibration = min(max(
                (1 - alpha) * self._calibration + alpha * ratio,
                0.05), 20.0)
            self._calibration_n += 1
            debited = ticket.get("debited_ms", 0.0)
            if debited > measured_ms:
                self.buckets[ticket["class"]].credit(
                    debited - measured_ms)
            self._cond.notify_all()

    # -- degradation ladder ------------------------------------------------

    def maybe_update_ladder(self, now=None):
        """Re-derive the ladder state from SLO burn + devhealth, rate-
        limited to LADDER_SAMPLE_INTERVAL. Escalation is immediate;
        de-escalation steps one rung per LADDER_HOLD_SECONDS so the
        ladder can't flap with a noisy burn signal."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            if now - self._ladder_checked < LADDER_SAMPLE_INTERVAL:
                return self.state
            self._ladder_checked = now
        target, reason = self._target_state()
        with self._lock:
            cur = self.state
            if target == cur:
                return cur
            if STATE_RANK[target] > STATE_RANK[cur]:
                new = target  # escalate straight to the signal's rung
            else:
                if now - self.state_since < LADDER_HOLD_SECONDS:
                    return cur
                new = STATES[STATE_RANK[cur] - 1]  # step down one rung
                reason = f"recovering (target {target})"
            self.state = new
            self.state_since = now
            self.transitions.append({
                "from": cur, "to": new, "reason": reason,
                "at": time.time()})
            del self.transitions[:-50]
            self._cond.notify_all()
        self._record_transition(cur, new, reason)
        return new

    def _target_state(self):
        """(state, reason) the signals currently call for."""
        from ..utils import devhealth
        from ..utils import workload as workload_mod

        if devhealth.is_down():
            return LIFEBOAT, "device link DOWN"
        slo = workload_mod.slo()
        summary = slo.summary()
        alerting = summary.get("alerting") or []
        worst = summary.get("worst_fast_burn", 0.0)
        threshold = getattr(slo, "burn_threshold", 6.0) or 6.0
        if alerting:
            if worst >= threshold * LIFEBOAT_BURN_FACTOR:
                return LIFEBOAT, (
                    f"burn {worst:.1f}x budget "
                    f">= {LIFEBOAT_BURN_FACTOR:g}x threshold")
            if worst >= threshold * STALE_BURN_FACTOR:
                return STALE_OK, (
                    f"burn {worst:.1f}x budget "
                    f">= {STALE_BURN_FACTOR:g}x threshold")
            return SHED_BATCH, (
                "SLO alerting: " + ",".join(map(str, alerting)))
        if devhealth.state() == devhealth.DEGRADED:
            return SHED_BATCH, "device link DEGRADED"
        return NORMAL, "signals nominal"

    def _record_transition(self, old, new, reason):
        from ..utils import flightrec
        from ..utils.stats import global_stats

        flightrec.record("admission.state", from_state=old, to=new,
                         reason=reason)
        global_stats.count("admission_transitions", 1,
                           {"from": old, "to": new})
        if self.logger is not None:
            try:
                self.logger.printf(
                    f"admission: {old} -> {new} ({reason})")
            except Exception:  # noqa: BLE001 — logging is best-effort
                pass

    def serving_stale(self):
        """True when responses should carry the `stale` marker: the
        ladder is at STALE_OK or worse, so reads are served from
        resident stacks while ingest merges are deferred."""
        return STATE_RANK[self.state] >= STATE_RANK[STALE_OK]

    def shed_merges(self):
        """Ingest shed-policy probe: defer interval merges from
        SHED_BATCH up (overflow-forced merges still run — the engine
        distinguishes the wake cause)."""
        return STATE_RANK[self.state] >= STATE_RANK[SHED_BATCH]

    # -- lifecycle / observability -----------------------------------------

    def close(self):
        with self._lock:
            self._closed = True
            self._cond.notify_all()
        try:
            _REGISTRY.remove(self)
        except ValueError:
            pass

    def snapshot(self):
        """GET /debug/admission payload."""
        now = time.monotonic()
        with self._lock:
            classes = {}
            for c in CLASSES:
                b = self.buckets[c]
                b.refill(now)
                classes[c] = {
                    "share": self.shares[c],
                    "rate_ms_per_s": round(b.rate, 3),
                    "tokens_ms": round(b.tokens, 3),
                    "burst_ms": round(b.burst, 3),
                    "admitted": self.admitted[c],
                    "rejected": self.rejected[c],
                    "queued_total": self.queued[c],
                    "expired_dropped": self.expired[c],
                    "waiting_now": self._waiting[c],
                }
            return {
                "enabled": True,
                "state": self.state,
                "state_rank": STATE_RANK[self.state],
                "state_age_seconds": round(now - self.state_since, 3),
                "capacity_ms_per_s": self.capacity,
                "queue_depth": self.queue_depth,
                "queue_timeout_seconds": self.queue_timeout,
                "calibration": round(self._calibration, 4),
                "calibration_samples": self._calibration_n,
                "classes": classes,
                "shed_by_state": dict(self.shed_by_state),
                "transitions": list(self.transitions),
            }

    def summary(self):
        """Compact roll-up for /status observability."""
        with self._lock:
            return {
                "state": self.state,
                "admitted": sum(self.admitted.values()),
                "rejected": sum(self.rejected.values()),
                "expired_dropped": sum(self.expired.values()),
                "waiting_now": sum(self._waiting.values()),
            }
