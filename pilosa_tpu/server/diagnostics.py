"""Diagnostics phone-home (reference: diagnostics.go:41-260 + the hourly
loop server.go:760-810).

Collects anonymized cluster info and POSTs it to a configured endpoint on an
interval, and parses the response for a newer-version notice. Disabled by
default (`diagnostics.enabled = false`, and unlike the reference there is no
default public endpoint — an explicit URL is required), so nothing ever
leaves the host unless an operator opts in.
"""

import json
import threading
import time
import urllib.request

from .. import __version__


def _version_tuple(v):
    return tuple(int(p) for p in str(v).strip().lstrip("v").split(".")[:3]
                 if p.isdigit())


class Diagnostics:
    def __init__(self, api, endpoint, interval=3600.0, logger=None):
        from ..utils.logger import NopLogger

        self.api = api
        self.endpoint = endpoint
        self.interval = max(float(interval), 10.0)
        self.logger = logger if logger is not None else NopLogger()
        self.last_response = None
        self._stop = threading.Event()
        self._thread = None
        self._t0 = time.time()

    # -- payload (reference: diagnostics.go EnrichWithOSInfo/CheckVersion) ---

    def payload(self):
        """Anonymized cluster snapshot: counts and versions only — no
        index/field names, keys, or addresses (reference: diagnostics.go
        sends similarly shaped metrics)."""
        import platform

        holder = self.api.holder
        indexes = list(holder.indexes.values())
        n_fields = sum(len(i.fields) for i in indexes)
        n_shards = sum(len(i.available_shards()) for i in indexes)
        cluster = self.api.cluster
        try:
            import jax

            backend = jax.default_backend()
            n_devices = jax.device_count()
        except Exception:
            backend, n_devices = "none", 0
        return {
            "version": __version__,
            "os": platform.system(),
            "python": platform.python_version(),
            "numIndexes": len(indexes),
            "numFields": n_fields,
            "numShards": n_shards,
            "numNodes": len(cluster.nodes) if cluster else 1,
            "replicaN": cluster.replica_n if cluster else 1,
            "backend": backend,
            "numDevices": n_devices,
            "uptimeSeconds": int(time.time() - self._t0),
        }

    def flush(self):
        """One POST + version check; never raises (reference: diagnostics
        errors are logged and ignored)."""
        try:
            req = urllib.request.Request(
                self.endpoint, data=json.dumps(self.payload()).encode(),
                method="POST", headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                body = resp.read()
            self.last_response = json.loads(body) if body else {}
            self.check_version(self.last_response)
        except Exception as e:
            self.logger.debugf("diagnostics flush failed: %s", e)

    def check_version(self, response):
        """Log when the endpoint reports a newer version (reference:
        diagnostics.CheckVersion diagnostics.go:179)."""
        latest = (response or {}).get("version")
        if latest and _version_tuple(latest) > _version_tuple(__version__):
            self.logger.printf(
                "newer pilosa_tpu version available: %s (running %s)",
                latest, __version__)
            return True
        return False

    # -- loop ----------------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name="pilosa-diagnostics", daemon=True)
        self._thread.start()
        return self

    def _run(self):
        self.flush()
        while not self._stop.wait(self.interval):
            self.flush()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
